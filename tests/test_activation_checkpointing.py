"""Activation checkpointing: remat numerics identical, memory policies
apply, RNG reproducibility under recompute (reference
tests/unit/test_activation_checkpointing.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ck


def _block(p, x, rng=None):
    h = x @ p["w"]
    if rng is not None:
        keep = jax.random.bernoulli(rng, 0.9, h.shape)
        h = jnp.where(keep, h / 0.9, 0.0)
    return jax.nn.gelu(h)


def _stacked_params(L, d, seed=0):
    k = jax.random.PRNGKey(seed)
    return {"w": jax.random.normal(k, (L, d, d), jnp.float32) / np.sqrt(d)}


def test_checkpoint_same_value_and_grad():
    from tests.capabilities import REMAT_BITEXACT_SKIP, remat_grads_bitexact

    if not remat_grads_bitexact():
        pytest.skip(REMAT_BITEXACT_SKIP)

    d = 16
    p = {"w": jax.random.normal(jax.random.PRNGKey(0), (d, d), jnp.float32)}
    x = jax.random.normal(jax.random.PRNGKey(1), (4, d), jnp.float32)

    def loss_plain(p):
        return jnp.sum(_block(p, x) ** 2)

    def loss_ck(p):
        return jnp.sum(ck.checkpoint(_block, p, x) ** 2)

    v1, g1 = jax.value_and_grad(loss_plain)(p)
    v2, g2 = jax.value_and_grad(loss_ck)(p)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]), rtol=1e-6)


def test_checkpoint_rng_reproducible():
    """Recompute must see identical randomness (the reference's RNG
    fork/restore machinery, checkpointing.py:122-238 — free in JAX)."""
    from tests.capabilities import REMAT_BITEXACT_SKIP, remat_grads_bitexact

    if not remat_grads_bitexact():
        pytest.skip(REMAT_BITEXACT_SKIP)
    d = 16
    p = {"w": jax.random.normal(jax.random.PRNGKey(0), (d, d), jnp.float32)}
    x = jax.random.normal(jax.random.PRNGKey(1), (4, d), jnp.float32)
    rng = jax.random.PRNGKey(42)

    def loss_plain(p):
        return jnp.sum(_block(p, x, rng) ** 2)

    def loss_ck(p):
        return jnp.sum(ck.checkpoint(_block, p, x, rng) ** 2)

    v1, g1 = jax.value_and_grad(loss_plain)(p)
    v2, g2 = jax.value_and_grad(loss_ck)(p)
    np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]), rtol=1e-6)


@pytest.mark.parametrize("every", [1, 2, 4])
def test_checkpoint_sequential_matches_plain_scan(every):
    L, d = 4, 8
    params = _stacked_params(L, d)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, d), jnp.float32)

    def plain(params):
        def body(h, p):
            return _block(p, h), None

        h, _ = jax.lax.scan(body, x, params)
        return jnp.sum(h ** 2)

    def remat(params):
        return jnp.sum(ck.checkpoint_sequential(_block, params, x, every=every) ** 2)

    v1, g1 = jax.value_and_grad(plain)(params)
    v2, g2 = jax.value_and_grad(remat)(params)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]), rtol=1e-5)


def test_checkpoint_sequential_bad_interval():
    params = _stacked_params(4, 8)
    x = jnp.zeros((2, 8))
    with pytest.raises(AssertionError):
        ck.checkpoint_sequential(_block, params, x, every=3)


def test_configure_from_dict_and_args():
    from deepspeed_tpu.config.config import DeepSpeedConfig

    cfg = DeepSpeedConfig(
        {
            "train_micro_batch_size_per_gpu": 1,
            "activation_checkpointing": {"partition_activations": True, "cpu_checkpointing": True},
        },
        world_size=1,
    )
    ck.configure(deepspeed_config=cfg)
    assert ck.get_config().partition_activations
    assert ck.get_config().cpu_checkpointing
    ck.configure(partition_activations=False, checkpoint_in_cpu=False)
    assert not ck.get_config().partition_activations
    assert not ck.get_config().cpu_checkpointing


def test_rng_tracker_api():
    tr = ck.CudaRNGStatesTracker()
    tr.add("model-parallel-rng", 123)
    with pytest.raises(Exception):
        tr.add("model-parallel-rng", 5)
    before = tr.get_states()["model-parallel-rng"]
    with tr.fork():
        pass
    after = tr.get_states()["model-parallel-rng"]
    assert not np.array_equal(np.asarray(before), np.asarray(after))
    ck.model_parallel_cuda_manual_seed(7)
    assert "model-parallel-rng" in ck.get_cuda_rng_tracker().get_states()
