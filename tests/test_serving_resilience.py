"""Serving resilience tests (ISSUE 10; docs/serving.md §Resilience).

The chaos matrix: seeded kill mid-decode → restart → journal replay
with outputs bit-matching an uninterrupted run; SIGTERM mid-prefill →
graceful drain → exit 43 only after the journal commits; overload at
far-past-capacity → estimated-TTFT shed with ``retry_after`` + the
degradation ladder engaging and disengaging with hysteresis; injected
journal-commit failure → clean quarantine.  Plus the idle-engine
queued-deadline sweep regression and the journal's torn-tail /
compaction unit behavior.
"""
import dataclasses
import os
import signal
import time

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfigError, ServingConfig
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving import (
    PRIORITY_HIGH,
    PRIORITY_LOW,
    RequestJournal,
    ServingDraining,
    ServingEngine,
    ServingOverloaded,
    ServingQueueFull,
)
from deepspeed_tpu.serving import journal as journal_mod

TINY = dataclasses.replace(gpt2.GPT2_TINY, remat=False)


@pytest.fixture(scope="module")
def eng():
    """Position-sensitive engine (wpe scaled) shared across the module —
    slot/position bugs change generations instead of hiding."""
    params = gpt2.init_params(TINY, seed=7)
    params["wpe"] = params["wpe"] * 40.0
    return deepspeed_tpu.init_inference(
        model_config=TINY, params=params, dtype=jnp.float32,
        max_out_tokens=TINY.n_positions,
    )


def _prompts(n, lo, hi, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, TINY.vocab_size, rng.integers(lo, hi + 1), dtype=np.int32)
        for _ in range(n)
    ]


def _srv(eng, tmp_path=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_len", 64)
    if tmp_path is not None:
        kw.setdefault("journal_dir", str(tmp_path / "journal"))
    return ServingEngine(eng, **kw)


# ---------------------------------------------------------------------------
# journal unit behavior (no engine)
# ---------------------------------------------------------------------------

class _Req:
    """Duck-typed scheduler Request for journal unit tests."""

    def __init__(self, rid, prompt=(1, 2, 3), max_new=4, **kw):
        self.request_id = rid
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new_tokens = max_new
        self.eos_token_id = kw.get("eos")
        self.priority = kw.get("priority", 1)
        self.deadline_seconds = None
        self.do_sample = kw.get("do_sample", False)
        self.temperature = kw.get("temperature", 1.0)
        self.top_k = kw.get("top_k", 0)
        self.seed = kw.get("seed", 0)
        self.generated = kw.get("generated", [])
        self.finish_reason = kw.get("finish_reason")


def test_journal_submit_retire_incomplete(tmp_path):
    j = RequestJournal(str(tmp_path / "j"))
    for rid in range(4):
        j.record_submit(_Req(rid, prompt=[rid + 1], max_new=3 + rid))
    j.record_retire(_Req(1, finish_reason="length"))
    j.record_retire(_Req(3, finish_reason="eos"))
    j.commit()
    inc = j.incomplete()
    assert [e["id"] for e in inc] == [0, 2]
    assert inc[0]["prompt"] == [1] and inc[0]["max_new"] == 3
    # the admit record's EFFECTIVE budget (degradation clamp) wins
    r2 = _Req(2, max_new=2)
    j.record_admit(r2)
    j.commit()
    assert [e["max_new"] for e in j.incomplete()] == [3, 2]
    j.close()


def test_journal_torn_tail_dropped_and_corrupt_middle_raises(tmp_path):
    path = str(tmp_path / "j")
    j = RequestJournal(path)
    j.record_submit(_Req(0))
    j.record_submit(_Req(1))
    j.commit()
    j.close()
    seg = os.path.join(path, sorted(os.listdir(path))[0])
    # torn tail: append half a record (a crash mid-append)
    with open(seg, "a") as f:
        f.write('{"t":"submit","id":2,')
    inc = journal_mod.incomplete_requests(path)
    assert [e["id"] for e in inc] == [0, 1]
    # corrupt a MIDDLE line -> not a torn tail -> raises
    with open(seg) as f:
        lines = f.readlines()
    lines[0] = lines[0][:-12] + "00000000\n"  # break the first record's crc
    with open(seg, "w") as f:
        f.writelines(lines)
    with pytest.raises(journal_mod.JournalError, match="not a torn tail"):
        journal_mod.incomplete_requests(path)


def test_journal_rotation_and_compaction_bounded(tmp_path):
    path = str(tmp_path / "j")
    j = RequestJournal(path, segment_records=4, keep_segments=2)
    for rid in range(40):
        j.record_submit(_Req(rid))
        if rid % 2 == 0:
            j.record_retire(_Req(rid, finish_reason="length"))
    j.commit()
    segs = [n for n in os.listdir(path) if n.startswith("wal_")]
    # compaction keeps the sealed-segment count bounded
    assert len(segs) <= 2 + 2, segs  # keep_segments + compact + active
    inc = j.incomplete()
    assert [e["id"] for e in inc] == [r for r in range(40) if r % 2 == 1]
    j.close()
    # a reopened journal starts a FRESH segment and sees the same set
    j2 = RequestJournal(path, segment_records=4, keep_segments=2)
    assert [e["id"] for e in j2.incomplete()] == [r for r in range(40) if r % 2 == 1]
    j2.close()


# ---------------------------------------------------------------------------
# chaos: seeded kill mid-decode -> restart -> replay parity
# ---------------------------------------------------------------------------

def test_kill_mid_decode_restart_replays_bit_identical(eng, tmp_path):
    """The acceptance proof (in-process InjectedKill form; the real
    ``kill -9`` form runs in tools/serving_chaos.py and the
    serving-chaos CI job): a death mid-decode loses the process state,
    a fresh engine over the same journal replays every incomplete
    request, and greedy AND seeded-sampling outputs bit-match the
    uninterrupted run."""
    prompts = _prompts(5, 4, 20, seed=1)
    budgets = [6, 3, 5, 2, 4]

    def submit_all(srv):
        rids = []
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            # request 2 samples (seeded) — replay must reproduce it too
            kw = dict(do_sample=True, temperature=0.9, top_k=8, seed=123) if i == 2 else {}
            rids.append(srv.submit(p, max_new_tokens=n, **kw))
        return rids

    # uninterrupted reference
    srv_ref = _srv(eng, tmp_path=None)
    rids_ref = submit_all(srv_ref)
    res_ref = srv_ref.drain(max_steps=500)
    expect = [res_ref[r].tokens() for r in rids_ref]

    # killed run: die on the 3rd decode dispatch
    srv1 = _srv(eng, tmp_path=tmp_path)
    rids1 = submit_all(srv1)
    inj = faults.FaultInjector(seed=0).kill("serving.decode", after=2)
    with pytest.raises(faults.InjectedKill):
        with inj:
            srv1.drain(max_steps=500)
    finished_before = set(srv1.scheduler._finished)

    # restart: a FRESH engine over the same journal dir
    srv2 = _srv(eng, tmp_path=tmp_path)
    replayed = srv2.recover()
    assert set(replayed) == set(rids1) - finished_before
    assert replayed, "the kill must leave incomplete requests"
    res2 = srv2.drain(max_steps=500)
    for rid, exp in zip(rids1, expect):
        if rid in replayed:
            np.testing.assert_array_equal(res2[rid].tokens(), exp)
    # idempotent: a second recover on the same engine is a no-op
    assert srv2.recover() == []


def test_recover_without_journal_or_empty_is_noop(eng, tmp_path):
    assert _srv(eng).recover() == []
    srv = _srv(eng, tmp_path=tmp_path)
    assert srv.recover() == []


def test_restart_submit_before_recover_never_reuses_journaled_ids(eng, tmp_path):
    """Id-reuse guard: a restarted process (fresh id counter) that
    submits BEFORE recover() must not hand out an incomplete journaled
    id — the new request's retire record would silently drop the old
    acknowledged request from the replay set."""
    from deepspeed_tpu.serving import scheduler as sched_mod

    srv = _srv(eng, tmp_path=tmp_path)
    old = srv.submit(_prompts(1, 6, 6, seed=21)[0], max_new_tokens=3)
    # "restart": the process-global id counter starts over...
    sched_mod._REQUEST_IDS._n = -1
    srv2 = _srv(eng, tmp_path=tmp_path)  # ...but the journal open bumps it
    fresh = srv2.submit(_prompts(1, 6, 6, seed=22)[0], max_new_tokens=3)
    assert fresh > old
    srv2.drain(max_steps=300)  # fresh request retires
    inc = journal_mod.incomplete_requests(str(tmp_path / "journal"))
    assert old in [e["id"] for e in inc]  # the acknowledged request survived
    assert srv2.recover() == [old]
    res = srv2.drain(max_steps=300)
    assert res[old].finish_reason == "length"


def test_journal_compacts_on_open_under_restart_loop(eng, tmp_path):
    """A crash-looping service constructs a journal per restart without
    ever reaching count-based rotation; construction-time compaction
    must bound the segment count anyway."""
    path = str(tmp_path / "j")
    for i in range(12):
        j = RequestJournal(path, segment_records=512, keep_segments=3)
        j.record_submit(_Req(i))
        if i % 2:
            j.record_retire(_Req(i, finish_reason="length"))
        j.commit()
        j.close()
    segs = [n for n in os.listdir(path) if n.startswith("wal_")]
    assert len(segs) <= 3 + 2, segs  # keep_segments + compact + active
    j = RequestJournal(path, segment_records=512, keep_segments=3)
    assert [e["id"] for e in j.incomplete()] == [i for i in range(12) if not i % 2]
    assert j.last_request_id == 11
    j.close()


# ---------------------------------------------------------------------------
# chaos: SIGTERM mid-prefill -> graceful drain -> exit 43
# ---------------------------------------------------------------------------

def test_sigterm_mid_prefill_drains_and_exits_43(eng, tmp_path):
    """SIGTERM while a multi-chunk prompt is mid-prefill: admission
    stops (ServingDraining with retry_after), the in-flight request
    finishes inside the drain budget, the queued one persists in the
    journal, and the exit code is 43 — raised only after the journal
    committed the drain record."""
    srv = _srv(eng, tmp_path=tmp_path, num_slots=1)
    long_prompt = _prompts(1, 24, 24, seed=3)[0]  # 3 chunks of 8
    r_flight = srv.submit(long_prompt, max_new_tokens=3)
    r_queued = srv.submit(_prompts(1, 6, 6, seed=4)[0], max_new_tokens=3)
    srv.install_watchdog(drain_deadline_seconds=60.0)
    try:
        srv.step()  # first chunk lands; prefill is mid-flight
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(ServingDraining) as exc:
            srv.submit(_prompts(1, 4, 4, seed=5)[0], max_new_tokens=2)
        assert exc.value.retry_after is not None
        with pytest.raises(SystemExit) as e:
            srv.step()
        assert e.value.code == 43
    finally:
        srv._watchdog.uninstall()
    # the in-flight request drained; the queued one is durable undone work
    assert srv.result(r_flight).finish_reason == "length"
    inc = journal_mod.incomplete_requests(str(tmp_path / "journal"))
    assert [e["id"] for e in inc] == [r_queued]
    recs = journal_mod.read_records(str(tmp_path / "journal"))
    drains = [r for r in recs if r["t"] == "drain"]
    assert drains and drains[-1]["undone"] == [r_queued]
    # and the replayed queued request completes on a restarted engine
    srv2 = _srv(eng, tmp_path=tmp_path, num_slots=1)
    assert srv2.recover() == [r_queued]
    res = srv2.drain(max_steps=300)
    assert res[r_queued].finish_reason == "length"


def test_sigterm_journal_commit_failure_exits_1(eng, tmp_path):
    """Exit 43 must CERTIFY the commit: an injected commit failure at
    drain time quarantines the journal and exits 1 (crash contract)."""
    srv = _srv(eng, tmp_path=tmp_path, num_slots=1)
    srv.submit(_prompts(1, 6, 6, seed=6)[0], max_new_tokens=2)
    srv.install_watchdog(drain_deadline_seconds=60.0)
    try:
        srv.step()
        os.kill(os.getpid(), signal.SIGTERM)
        # the drain-record commit is the LAST commit; fail exactly there
        inj = faults.FaultInjector(seed=0).fail("serving.journal.commit", times=99)
        with inj:
            with pytest.raises(SystemExit) as e:
                srv.step()
        assert e.value.code == 1
    finally:
        srv._watchdog.uninstall()
    assert srv.stats()["journal"] == "quarantined"


def test_sigterm_without_journal_full_drain_is_43_undone_is_1(eng):
    # fully drained, nothing undone -> 43 even without a journal
    srv = _srv(eng, num_slots=1)
    srv.submit(_prompts(1, 6, 6, seed=7)[0], max_new_tokens=2)
    srv.step()  # in-flight (a QUEUED request would be undone: exit 1)
    srv.install_watchdog(drain_deadline_seconds=60.0)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(SystemExit) as e:
            srv.step()
        assert e.value.code == 43
    finally:
        srv._watchdog.uninstall()
    # undone work with nowhere durable to live -> 1
    srv2 = _srv(eng, num_slots=1)
    srv2.submit(_prompts(1, 6, 6, seed=8)[0], max_new_tokens=2)
    srv2.submit(_prompts(1, 6, 6, seed=9)[0], max_new_tokens=2)  # queued
    srv2.install_watchdog(drain_deadline_seconds=0.0)  # no drain budget
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(SystemExit) as e:
            srv2.step()
        assert e.value.code == 1
    finally:
        srv2._watchdog.uninstall()


# ---------------------------------------------------------------------------
# chaos: overload -> shed with retry_after + degradation ladder
# ---------------------------------------------------------------------------

def test_overload_sheds_with_retry_after_and_keeps_admitted_ttft(eng):
    """Offered load far past capacity (every step costs an injected
    20ms, submits arrive back-to-back — well beyond 4x the measured
    service rate): the estimated-TTFT shedder rejects with a positive
    ``retry_after`` and the ADMITTED requests' p99 TTFT stays within
    the configured SLO."""
    slo_ms = 400.0
    srv = _srv(eng, slo_ttft_ms=slo_ms, max_queue=256)
    prompts = _prompts(40, 6, 8, seed=10)
    inj = faults.FaultInjector(seed=0).latency("serving.decode", seconds=0.02)
    with inj:
        # warm: the EWMA must see the slow decode before the blast
        # (HIGH priority: an armed process-wide telemetry plane may hold
        # stale step walls from other engines, and warm-up must admit)
        srv.submit(prompts[0], max_new_tokens=3, priority=PRIORITY_HIGH)
        srv.drain(max_steps=50)
        admitted, sheds = [], []
        for p in prompts[1:]:
            try:
                admitted.append(srv.submit(p, max_new_tokens=4))
            except ServingOverloaded as exc:
                sheds.append(exc)
            srv.step()
        res = srv.drain(max_steps=2000)
    assert sheds, "4x+ overload must shed"
    assert admitted, "the shedder must not starve the engine"
    for exc in sheds:
        assert exc.retry_after is not None and exc.retry_after > 0
    ttfts = [
        (res[r].first_token_time - res[r].submit_time) * 1e3
        for r in admitted if res[r].first_token_time is not None
    ]
    assert ttfts
    p99 = float(np.percentile(ttfts, 99))
    assert p99 <= slo_ms, (p99, len(admitted), len(sheds))
    assert srv.stats()["shed"] == len(sheds)


def test_high_priority_bypasses_ttft_shed(eng):
    srv = _srv(eng, slo_ttft_ms=1.0, max_queue=64)  # absurdly tight SLO
    inj = faults.FaultInjector(seed=0).latency("serving.decode", seconds=0.02)
    with inj:
        # high-priority warm-up: must admit even against a stale armed-
        # plane step-wall window (order-independence in the full suite)
        srv.submit(
            _prompts(1, 6, 6, seed=11)[0], max_new_tokens=4,
            priority=PRIORITY_HIGH,
        )
        for _ in range(3):
            srv.step()
        with pytest.raises(ServingOverloaded):
            srv.submit(_prompts(1, 6, 6, seed=12)[0], max_new_tokens=4)
        rid = srv.submit(
            _prompts(1, 6, 6, seed=13)[0], max_new_tokens=2, priority=PRIORITY_HIGH
        )
        res = srv.drain(max_steps=500)
    assert res[rid].finish_reason == "length"


def test_degradation_ladder_engages_clamps_sheds_and_disengages(eng):
    """Sustained queue pressure climbs the ladder rung by rung: clamped
    admits, a shrunk prefill budget, shed low-priority waiters carrying
    retry_after — then hysteresis steps it back down once calm."""
    srv = _srv(
        eng, num_slots=1, max_queue=8, slo_ttft_ms=0.0,
        degrade_queue_watermark=0.5, degrade_engage_steps=2,
        degrade_disengage_steps=4, degrade_max_new_tokens=2,
    )
    prompts = _prompts(40, 6, 8, seed=14)
    srv.submit(prompts[0], max_new_tokens=24)
    levels = set()
    for i, p in enumerate(prompts[1:]):
        try:
            srv.submit(p, max_new_tokens=24, priority=PRIORITY_LOW if i % 2 else 1)
        except ServingQueueFull:
            pass
        srv.step()
        levels.add(srv.scheduler.ladder.level)
    assert levels >= {0, 1, 2, 3}, levels
    s = srv.stats()
    assert s["degrade_engagements"] >= 3
    res = srv.drain(max_steps=3000)
    shed = [r for r in res.values() if r.finish_reason == "shed"]
    clamped = [r for r in res.values() if r.degraded]
    assert shed and all(r.retry_after and r.retry_after > 0 for r in shed)
    assert clamped and all(r.max_new_tokens == 2 for r in clamped)
    assert all(len(r.generated) <= 2 for r in clamped)
    # calm: the ladder steps all the way back down (hysteresis pace)
    for _ in range(30):
        srv.step()
    assert srv.scheduler.ladder.level == 0
    assert srv.stats()["degrade_level"] == 0


def test_queue_full_rejection_carries_retry_after(eng):
    srv = _srv(eng, num_slots=1, max_queue=1)
    p = _prompts(3, 4, 4, seed=15)
    srv.submit(p[0], max_new_tokens=4)
    srv.step()
    srv.submit(p[1], max_new_tokens=4)
    with pytest.raises(ServingQueueFull) as e:
        srv.submit(p[2], max_new_tokens=4)
    assert not isinstance(e.value, ServingOverloaded)
    assert e.value.retry_after is not None and e.value.retry_after > 0
    srv.drain(max_steps=200)


def test_priority_admission_order(eng):
    """With one slot busy, a later high-priority submit is admitted
    before earlier normal/low ones (FIFO within a tier)."""
    srv = _srv(eng, num_slots=1)
    p = _prompts(4, 4, 4, seed=16)
    srv.submit(p[0], max_new_tokens=2)
    srv.step()  # p0 holds the slot
    r_low = srv.submit(p[1], max_new_tokens=2, priority=PRIORITY_LOW)
    r_norm = srv.submit(p[2], max_new_tokens=2)
    r_high = srv.submit(p[3], max_new_tokens=2, priority=PRIORITY_HIGH)
    res = srv.drain(max_steps=300)
    assert res[r_high].admit_step < res[r_norm].admit_step < res[r_low].admit_step


# ---------------------------------------------------------------------------
# chaos: injected journal-commit failure -> clean quarantine
# ---------------------------------------------------------------------------

def test_journal_commit_failure_quarantines_and_serving_continues(eng, tmp_path):
    srv = _srv(eng, tmp_path=tmp_path)
    p = _prompts(3, 5, 9, seed=17)
    r0 = srv.submit(p[0], max_new_tokens=3)
    inj = faults.FaultInjector(seed=0).fail("serving.journal.commit")
    with inj:
        r1 = srv.submit(p[1], max_new_tokens=3)  # commit fails -> quarantine
    s = srv.stats()
    assert s["journal"] == "quarantined"
    qdirs = [d for d in os.listdir(tmp_path) if d.startswith("journal.corrupt")]
    assert qdirs and not os.path.exists(tmp_path / "journal")
    # serving is unaffected: both requests (and a post-quarantine one) finish
    r2 = srv.submit(p[2], max_new_tokens=3)
    res = srv.drain(max_steps=300)
    assert {r0, r1, r2} <= set(res)
    assert all(res[r].finish_reason == "length" for r in (r0, r1, r2))


# ---------------------------------------------------------------------------
# satellite: idle-engine queued-deadline sweep (regression)
# ---------------------------------------------------------------------------

def test_idle_engine_deadline_sweep_via_stats_and_drain(eng):
    """Regression: a request waiting in an engine nobody steps must
    still expire via the host-side sweep in stats()/drain()."""
    srv = _srv(eng, num_slots=1)
    p = _prompts(2, 4, 4, seed=18)
    r1 = srv.submit(p[0], max_new_tokens=4)
    srv.step()  # r1 occupies the only slot
    r2 = srv.submit(p[1], max_new_tokens=4, deadline_seconds=1e-9)
    time.sleep(0.002)
    # NO step between submit and stats: the sweep must fire on its own
    s = srv.stats()
    assert s["expired"] == 1
    r = srv.result(r2)
    assert r.status == "expired" and r.finish_reason == "expired"
    srv.drain(max_steps=200)
    # drain() path: same sweep at entry even when a step never runs
    srv2 = _srv(eng, num_slots=1)
    srv2.submit(p[0], max_new_tokens=4)
    srv2.step()
    r4 = srv2.submit(p[1], max_new_tokens=4, deadline_seconds=1e-9)
    time.sleep(0.002)
    res = srv2.drain(max_steps=200)
    assert res[r4].finish_reason == "expired"


def test_shed_then_crash_never_resurrects_shed_requests(eng, tmp_path):
    """Regression (ISSUE 14 satellite): a rung-3 ladder shed must
    journal its reject record IMMEDIATELY — a crash right after the
    shed (no drain, no clean close) must not let recover() resurrect
    the shed request, and the shed verdict must carry ``retry_after``
    (the backpressure hint the fleet router keys on)."""
    srv = _srv(
        eng, tmp_path=tmp_path, num_slots=1, max_queue=8, slo_ttft_ms=0.0,
        degrade_queue_watermark=0.5, degrade_engage_steps=2,
        degrade_disengage_steps=4, degrade_max_new_tokens=2,
    )
    prompts = _prompts(40, 6, 8, seed=23)
    submitted = [srv.submit(prompts[0], max_new_tokens=24)]
    shed_ids = []
    for i, p in enumerate(prompts[1:]):
        try:
            submitted.append(
                srv.submit(p, max_new_tokens=24,
                           priority=PRIORITY_LOW if i % 2 else 1)
            )
        except ServingQueueFull:
            pass
        srv.step()
        shed_ids = [r.request_id for r in srv.scheduler._finished.values()
                    if r.finish_reason == "shed"]
        if shed_ids:
            break
    assert shed_ids, "the ladder must reach the shed rung"
    for rid in shed_ids:
        assert srv.result(rid).retry_after > 0  # hint rides the verdict
    # crash NOW: no drain, no final commit — only what the shed itself
    # committed survives (the bug was a reject record that only reached
    # the journal on the next unrelated commit)
    del srv
    srv2 = _srv(eng, tmp_path=tmp_path, num_slots=1)
    replayed = srv2.recover()
    assert not set(replayed) & set(shed_ids), (replayed, shed_ids)
    res = srv2.drain(max_steps=3000)
    assert all(res[r].finish_reason != "shed" for r in replayed)


def test_expired_via_sweep_is_durable_in_journal(eng, tmp_path):
    srv = _srv(eng, tmp_path=tmp_path, num_slots=1)
    p = _prompts(2, 4, 4, seed=19)
    srv.submit(p[0], max_new_tokens=4)
    srv.step()
    r2 = srv.submit(p[1], max_new_tokens=4, deadline_seconds=1e-9)
    time.sleep(0.002)
    srv.stats()  # sweep + commit
    inc = journal_mod.incomplete_requests(str(tmp_path / "journal"))
    assert r2 not in [e["id"] for e in inc]  # expired == retired, never replays
    srv.drain(max_steps=200)


# ---------------------------------------------------------------------------
# fault-plan / config plumbing
# ---------------------------------------------------------------------------

def test_fault_plan_latency_action_round_trips():
    inj = faults.FaultInjector(seed=0)
    inj.latency("serving.decode", seconds=0.02, times=3)
    inj.fail("serving.journal.commit")
    spec = inj.to_plan()
    inj2 = faults.FaultInjector.from_plan(spec)
    with inj2:
        t0 = time.monotonic()
        assert faults.check_latency("serving.decode") == pytest.approx(0.02)
        assert time.monotonic() - t0 >= 0.02
        with pytest.raises(faults.InjectedFault):
            faults.check("serving.journal.commit")
    # unbounded latency plans keep firing
    inj3 = faults.FaultInjector(seed=0).latency("serving.decode", seconds=0.0)
    with inj3:
        for _ in range(5):
            faults.check_latency("serving.decode")
    assert inj3.calls("serving.decode") == 5


def test_serving_resilience_config_validation():
    with pytest.raises(DeepSpeedConfigError, match="degrade_queue_watermark"):
        ServingConfig.from_dict({"degrade_queue_watermark": 1.5})
    with pytest.raises(DeepSpeedConfigError, match="degrade_engage_steps"):
        ServingConfig.from_dict({"degrade_engage_steps": 0})
    with pytest.raises(DeepSpeedConfigError, match="slo_ttft_ms"):
        ServingConfig.from_dict({"slo_ttft_ms": -1})
    with pytest.raises(DeepSpeedConfigError, match="drain_deadline_seconds"):
        ServingConfig.from_dict({"drain_deadline_seconds": -1})
    with pytest.raises(DeepSpeedConfigError, match="journal_segment_records"):
        ServingConfig.from_dict({"journal_segment_records": 0})
    c = ServingConfig.from_dict(
        {"slo_ttft_ms": 250, "journal_dir": "/tmp/j", "degrade_max_new_tokens": 0}
    )
    assert c.slo_ttft_ms == 250 and c.journal_dir == "/tmp/j"


def test_submit_priority_validation(eng):
    srv = _srv(eng)
    with pytest.raises(ValueError, match="priority"):
        srv.submit(_prompts(1, 4, 4, seed=20)[0], max_new_tokens=2, priority=7)
