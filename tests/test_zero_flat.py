"""Flat-fallback ZeRO sharding: params with NO fsdp-divisible dimension
must still shard 1/W over the fsdp axis (the reference's flattened
contiguous partitions, stage2.py:432 / partition_parameters.py:688,
re-expressed as padded 1-D fsdp-sharded state leaves)."""
import os
import shutil

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu

# prime-ish dims: nothing divides by 8
D_IN, D_H, D_OUT = 131, 257, 127


def init_params(seed=0):
    r = np.random.default_rng(seed)
    return {
        "w1": (r.standard_normal((D_IN, D_H)) * 0.05).astype(np.float32),
        "b1": np.zeros((D_H,), np.float32),
        "w2": (r.standard_normal((D_H, D_OUT)) * 0.05).astype(np.float32),
        "b2": np.zeros((D_OUT,), np.float32),
    }


def model(params, batch, rng):
    x = batch["x"]
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    y = h @ params["w2"] + params["b2"]
    return jnp.mean((y - batch["y"]) ** 2)


def make_config(stage, fsdp=8, data=1):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        # the tiny test params sit below the stage-3 persistence
        # threshold default (100k) — lower it so they shard
        "zero_optimization": {"stage": stage, "stage3_param_persistence_threshold": 64},
        "mesh": {"data": data, "fsdp": fsdp},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 10_000,
    }


_TRUE_W = np.random.default_rng(7).standard_normal((D_IN, D_OUT)).astype(np.float32) * 0.1


def batches(n, global_bs=4 * 8):
    r = np.random.default_rng(1)
    for _ in range(n):
        x = r.standard_normal((global_bs, D_IN)).astype(np.float32)
        yield {"x": x, "y": x @ _TRUE_W}  # learnable target


def device_bytes(tree):
    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            total += leaf.addressable_shards[0].data.nbytes
    return total


def logical_bytes(tree):
    return sum(l.nbytes for l in jax.tree.leaves(tree) if hasattr(l, "nbytes"))


def test_flat_plan_covers_awkward_leaves():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=init_params(), config=make_config(3)
    )
    # every leaf has no 8-divisible dim -> all four in the plan
    assert len(engine._flat_plan) == 4
    for _, (shape, n, padded) in engine._flat_plan.items():
        assert padded % 8 == 0 and padded >= n


def test_zero3_per_device_param_bytes_one_eighth():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=init_params(), config=make_config(3)
    )
    total = (D_IN * D_H + D_H + D_H * D_OUT + D_OUT) * 4  # fp32 bytes
    per_dev = device_bytes(engine.state["params"])
    # per-device bytes ~ total/8 (padding adds <1%)
    assert per_dev < total / 8 * 1.05, (per_dev, total / 8)
    # optimizer m/v likewise sharded
    opt_per_dev = device_bytes(engine.state["opt_state"])
    opt_logical = logical_bytes(engine.state["opt_state"])
    assert opt_per_dev < opt_logical / 8 * 1.05 + 64


def test_zero1_opt_state_sharded_params_replicated():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=init_params(), config=make_config(1)
    )
    # params replicated (stage 1) — full bytes per device
    total = (D_IN * D_H + D_H + D_H * D_OUT + D_OUT) * 4
    assert device_bytes(engine.state["params"]) >= total
    opt_per_dev = device_bytes(engine.state["opt_state"])
    opt_logical = logical_bytes(engine.state["opt_state"])
    assert opt_per_dev < opt_logical / 8 * 1.05 + 64


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_flat_stages_match_stage0_numerics(stage):
    losses = {}
    for s in (0, stage):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=init_params(), config=make_config(s)
        )
        ls = [float(engine.train_batch(b)) for b in batches(5)]
        losses[s] = ls
    np.testing.assert_allclose(losses[0], losses[stage], rtol=2e-4, atol=2e-5)
    assert losses[0][0] > losses[0][-1]  # actually trains


def test_flat_checkpoint_roundtrip_and_resize(tmp_path):
    ck = str(tmp_path / "ck")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=init_params(), config=make_config(3, fsdp=8)
    )
    for b in batches(3):
        engine.train_batch(b)
    ref_losses = [float(engine.train_batch(b)) for b in batches(2)]
    # rewind: retrain 3 steps, save, restore into a DIFFERENT fsdp degree
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=init_params(), config=make_config(3, fsdp=8)
    )
    for b in batches(3):
        engine.train_batch(b)
    engine.save_checkpoint(ck, client_state={"k": 1})

    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=init_params(), config=make_config(3, fsdp=4, data=2)
    )
    path, client = engine2.load_checkpoint(ck)
    assert path is not None and client == {"k": 1}
    assert engine2.global_steps == engine.global_steps
    # padded sizes differ between fsdp=8 and fsdp=4 -> portable format
    losses2 = [float(engine2.train_batch(b)) for b in batches(2)]
    np.testing.assert_allclose(ref_losses, losses2, rtol=2e-4, atol=2e-5)
