"""Flash-attention kernel numerics vs reference (the reference's
test_cuda_forward.py / test_cuda_backward.py role: kernel vs framework
numerics)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention.flash_attention import (
    _blockwise_xla,
    flash_attention,
    mha_reference,
)


def qkv(b=2, h=4, sq=256, sk=256, d=64, dtype=jnp.float32, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(k1, (b, h, sq, d), dtype),
        jax.random.normal(k2, (b, h, sk, d), dtype),
        jax.random.normal(k3, (b, h, sk, d), dtype),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = qkv()
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_forward_rectangular_blocks():
    q, k, v = qkv(sq=128, sk=384)
    out = flash_attention(q, k, v, causal=False, block_q=64, block_k=128, interpret=True)
    ref = mha_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_blockwise_xla_matches_reference():
    q, k, v = qkv()
    out = _blockwise_xla(q, k, v, causal=True, sm_scale=q.shape[-1] ** -0.5, block_k=64)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [True, False])
def test_vmem_bound_attention_routes_through_splash(monkeypatch, causal):
    """Self-attention past the kernel's VMEM envelope routes to the
    splash kernel with a dense layout (tril when causal, all-ones
    otherwise — the all-full-degree exemption keeps every row on the
    streaming kernel); fwd AND grads must match the reference.  d=512
    trips the guard (sq*d*4*4 >= 8MB) at a CPU-testable sq=1024.  The
    route is pinned by a spy: _blockwise_xla matching the reference too
    would otherwise mask a lost/inverted routing condition."""
    from deepspeed_tpu.ops.attention import sparse as sparse_mod

    calls = []
    real_splash = sparse_mod.splash_attention

    def spy(*a, **kw):
        calls.append(1)
        return real_splash(*a, **kw)

    monkeypatch.setattr(sparse_mod, "splash_attention", spy)
    r = np.random.default_rng(11)
    B, H, T, d = 1, 2, 1024, 512
    q, k, v = (jnp.asarray(r.standard_normal((B, H, T, d)) * 0.1, jnp.float32) for _ in range(3))
    # the guard condition the route lives behind
    assert T * d * 4 * 4 >= 2**23

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    np.testing.assert_allclose(
        float(f_flash(q, k, v)), float(f_ref(q, k, v)), rtol=1e-4
    )
    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)
    assert calls, "flash_attention did not route through splash_attention"


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_reference(causal):
    q, k, v = qkv(b=1, h=2, sq=128, sk=128, d=32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=1e-4, rtol=1e-4)


def test_bf16_forward_close():
    q, k, v = qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32), causal=True
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2)


def test_tiny_shapes_fallback():
    q, k, v = qkv(sq=7, sk=7, d=16)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_short_seq_dense_dispatch_matches_kernel():
    """The default dispatch routes sq*sk <= 128^2 to the materializing
    dense path (TPU crossover, mha_dense); an explicitly-passed
    ``interpret`` keeps the Pallas kernel.  Both must agree — forward
    AND grads — so the dispatch seam can't drift."""
    q, k, v = qkv(b=3, h=2, sq=128, sk=128, d=32)
    bias = jnp.where(
        jax.random.uniform(jax.random.PRNGKey(7), (3, 1, 1, 128)) < 0.2, -1e9, 0.0
    ).astype(jnp.float32)
    dense = flash_attention(q, k, v, causal=True, bias=bias)  # dense shortcut
    kern = flash_attention(q, k, v, causal=True, bias=bias, interpret=True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(kern), atol=2e-5, rtol=2e-5)

    def loss(fn_kwargs):
        def f(q):
            return jnp.sum(flash_attention(q, k, v, causal=True, bias=bias, **fn_kwargs) ** 2)
        return jax.grad(f)(q)

    g_dense = loss({})
    g_kern = loss({"interpret": True})
    np.testing.assert_allclose(np.asarray(g_dense), np.asarray(g_kern), atol=3e-4, rtol=3e-4)


def test_backward_rectangular_causal():
    """sq < sk with end-aligned causal (chunked-prefill shape): the
    Pallas backward's causal offsets must match the reference."""
    q, k, v = qkv(b=1, h=2, sq=64, sk=128, d=32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=1e-4, rtol=1e-4)


def test_backward_uneven_blocks():
    """block_q != block_k and seq not a multiple of the other block."""
    q, k, v = qkv(b=1, h=1, sq=96, sk=96, d=16)
    g1 = jax.grad(lambda a: jnp.sum(flash_attention(a, k, v, causal=True, block_q=32, block_k=48, interpret=True) ** 2))(q)
    g2 = jax.grad(lambda a: jnp.sum(mha_reference(a, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4, rtol=1e-4)


# ---------------------------------------------------------------------------
# bias + attention-probability dropout through the kernels
# ---------------------------------------------------------------------------

def _rand_qkv(rng, b=2, h=3, sq=256, sk=256, d=64, dtype=jnp.float32):
    return (
        jnp.asarray(rng.standard_normal((b, h, sq, d)) * 0.3, dtype),
        jnp.asarray(rng.standard_normal((b, h, sk, d)) * 0.3, dtype),
        jnp.asarray(rng.standard_normal((b, h, sk, d)) * 0.3, dtype),
    )


@pytest.mark.parametrize("bias_shape", [(2, 1, 1, 256), (2, 3, 256, 256)])
def test_bias_matches_reference_fwd_and_grads(bias_shape):
    """Key-broadcast and full additive bias through the Pallas kernels
    (fwd + dq/dk/dv) against the XLA oracle."""
    r = np.random.default_rng(0)
    q, k, v = _rand_qkv(r)
    bias = jnp.asarray(np.where(r.random(bias_shape) < 0.2, -1e9, 0.0), jnp.float32)

    out = flash_attention(q, k, v, bias=bias, block_q=128, block_k=128)
    ref = mha_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, bias=bias, block_q=128, block_k=128) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, bias=bias) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("bias_shape", [(2, 1, 1, 256), (1, 3, 1, 256), (2, 3, 256, 256)])
@pytest.mark.parametrize("causal", [False, True])
def test_trainable_bias_cotangent_matches_reference(bias_shape, causal):
    """A TRAINABLE additive bias (learned relative-position / ALiBi
    style) gets its exact gradient through the kernel path — not zeros
    (ADVICE r2: zeros_like(bias) silently froze such parameters)."""
    r = np.random.default_rng(1)
    q, k, v = _rand_qkv(r)
    bias = jnp.asarray(r.standard_normal(bias_shape) * 0.5, jnp.float32)

    def f_flash(b_):
        return jnp.sum(flash_attention(q, k, v, bias=b_, causal=causal, block_q=128, block_k=128) ** 2)

    def f_ref(b_):
        return jnp.sum(mha_reference(q, k, v, bias=b_, causal=causal) ** 2)

    db1 = jax.grad(f_flash)(bias)
    db2 = jax.grad(f_ref)(bias)
    assert float(jnp.abs(db2).max()) > 1e-6  # the oracle gradient is non-trivial
    np.testing.assert_allclose(np.asarray(db1), np.asarray(db2), rtol=2e-4, atol=2e-4)


def test_dropout_matches_reference_with_same_mask():
    """Kernel dropout (fwd + grads) equals the oracle given the SAME
    keep-mask; the mask regenerates identically in the backward."""
    from deepspeed_tpu.ops.attention.flash_attention import _flash_attention

    r = np.random.default_rng(1)
    b, h, sq, sk, d = 2, 2, 256, 256, 64
    q, k, v = _rand_qkv(r, b, h, sq, sk, d)
    keep_prob = 0.8
    mask3 = jnp.asarray((r.random((b * h, sq, sk)) < keep_prob).astype(np.uint8))
    m4 = mask3.reshape(b, h, sq, sk)

    out = _flash_attention(q, k, v, None, mask3, None, False, d ** -0.5, 128, 128, True, keep_prob)
    ref = mha_reference(q, k, v, dropout_mask=m4, keep_prob=keep_prob)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def f_flash(q, k, v):
        return jnp.sum(_flash_attention(q, k, v, None, mask3, None, False, d ** -0.5, 128, 128, True, keep_prob) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, dropout_mask=m4, keep_prob=keep_prob) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-5)


def test_dropout_zero_rate_is_exact_and_public_api_runs():
    r = np.random.default_rng(2)
    q, k, v = _rand_qkv(r)
    out0 = flash_attention(q, k, v, causal=True)
    out1 = flash_attention(q, k, v, causal=True, dropout_rate=0.0)
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(out1))
    # public API with dropout: runs, differs from p=0, is differentiable
    rng = jax.random.PRNGKey(0)
    out_d = flash_attention(q, k, v, causal=True, dropout_rate=0.3, dropout_rng=rng)
    assert not np.allclose(np.asarray(out_d), np.asarray(out0))
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, causal=True, dropout_rate=0.3, dropout_rng=rng) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_bias_dropout_causal_combined():
    """All three features at once vs the oracle (same mask)."""
    from deepspeed_tpu.ops.attention.flash_attention import _flash_attention

    r = np.random.default_rng(3)
    b, h, t, d = 2, 2, 128, 64
    q, k, v = _rand_qkv(r, b, h, t, t, d)
    bias = jnp.asarray(np.where(r.random((b, 1, 1, t)) < 0.2, -1e9, 0.0), jnp.float32)
    keep = 0.9
    mask3 = jnp.asarray((r.random((b * h, t, t)) < keep).astype(np.uint8))
    out = _flash_attention(q, k, v, bias, mask3, None, True, d ** -0.5, 128, 128, True, keep)
    ref = mha_reference(q, k, v, causal=True, bias=bias, dropout_mask=mask3.reshape(b, h, t, t), keep_prob=keep)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_inkernel_dropout_matches_host_twin_mask():
    """r4 in-kernel dropout PRNG (VERDICT r3 #7): the kernels generate
    the keep-mask from a counter-based Threefry inside the kernel; the
    host twin (dropout_keep_mask_host) must reproduce it exactly, so
    kernel fwd+grads equal the oracle fed the host-generated mask."""
    from deepspeed_tpu.ops.attention.flash_attention import (
        _flash_attention, _seed_pair, dropout_keep_mask_host,
    )

    r = np.random.default_rng(7)
    b, h, sq, sk, d = 2, 2, 256, 256, 64
    q, k, v = _rand_qkv(r, b, h, sq, sk, d)
    keep_prob = 0.8
    seed = _seed_pair(jax.random.PRNGKey(123))
    m4 = dropout_keep_mask_host(seed, b, h, sq, sk, keep_prob).reshape(b, h, sq, sk)
    # keep statistics: the threshold rule must hit keep_prob closely
    frac = float(np.asarray(m4, np.float32).mean())
    assert abs(frac - keep_prob) < 0.01, frac

    out = _flash_attention(q, k, v, None, None, seed, False, d ** -0.5, 128, 128, True, keep_prob)
    ref = mha_reference(q, k, v, dropout_mask=m4, keep_prob=keep_prob)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def f_flash(q, k, v):
        return jnp.sum(_flash_attention(q, k, v, None, None, seed, False, d ** -0.5, 128, 128, True, keep_prob) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, dropout_mask=m4, keep_prob=keep_prob) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-5)


def test_inkernel_dropout_causal_and_blocking_invariance():
    """The mask is a pure function of absolute element position: kernel
    results must be identical across block decompositions (the dkv pass
    re-derives tiles under a different grid), and compose with causal."""
    from deepspeed_tpu.ops.attention.flash_attention import _flash_attention, _seed_pair

    r = np.random.default_rng(8)
    b, h, t, d = 1, 2, 256, 64
    q, k, v = _rand_qkv(r, b, h, t, t, d)
    seed = _seed_pair(jax.random.PRNGKey(5))
    keep = 0.9
    o128 = _flash_attention(q, k, v, None, None, seed, True, d ** -0.5, 128, 128, True, keep)
    o64 = _flash_attention(q, k, v, None, None, seed, True, d ** -0.5, 64, 64, True, keep)
    np.testing.assert_allclose(np.asarray(o128), np.asarray(o64), rtol=2e-5, atol=2e-5)

    def g(fn_blocks):
        bq, bk = fn_blocks
        return jax.grad(lambda q_: jnp.sum(
            _flash_attention(q_, k, v, None, None, seed, True, d ** -0.5, bq, bk, True, keep) ** 2
        ))(q)

    np.testing.assert_allclose(np.asarray(g((128, 128))), np.asarray(g((64, 64))), rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_long_seq_dropout_compiled_memory_bound():
    """The point of in-kernel dropout: training with attention dropout
    at 8k seq must NOT materialize the (B,H,Tq,Tk) keep-mask — compiled
    temp memory stays far below the 64MB/head the mask would cost
    (VERDICT r3 #7 'Done' criterion)."""
    b, h, t, d = 1, 2, 8192, 64
    rng = jax.random.PRNGKey(0)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, dropout_rate=0.1, dropout_rng=rng)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    # bf16: the kernel's VMEM envelope admits 8k×64 bf16 (fp32 tops out
    # just below 8k and would fall back to the materializing path)
    q = jnp.zeros((b, h, t, d), jnp.bfloat16)
    compiled = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q).compile()
    mem = compiled.memory_analysis()
    temp = getattr(mem, "temp_size_in_bytes", None)
    if temp is None:
        pytest.skip("backend exposes no memory_analysis temp sizes")
    mask_bytes = b * h * t * t  # uint8 keep-mask the old path materialized
    assert temp < mask_bytes // 2, (temp, mask_bytes)


def test_fused_bwd_chunked_matches_monolithic(monkeypatch):
    """The q-chunked fused backward (r5: sequences past the VMEM cap)
    must match the monolithic fused kernel bit-for-bit in structure:
    same grads, causal masking and the position-keyed dropout counter
    chunking-invariant.  The cap is shrunk so a small case chunks."""
    import deepspeed_tpu.ops.attention.flash_attention as fa

    b, h, sq, d = 1, 2, 512, 64
    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, sq, d)), jnp.float32) for _ in range(3))
    g = jnp.asarray(rng.standard_normal((b, h, sq, d)), jnp.float32)

    def grads(chunked, causal, seed=None):
        jax.clear_caches()  # the cap is read at trace time — force re-trace
        if chunked:
            monkeypatch.setattr(fa, "_FUSED_BWD_MAX_SQ_BYTES", 128 * d * 4)
        else:
            monkeypatch.setattr(fa, "_FUSED_BWD_MAX_SQ_BYTES", 1 << 21)
        kw = dict(causal=causal, block_q=128, block_k=128, interpret=True)
        if seed is not None:
            kw.update(dropout_rate=0.1, dropout_rng=jax.random.PRNGKey(seed))
        f = lambda q_, k_, v_: jnp.sum(
            fa.flash_attention(q_, k_, v_, **kw).astype(jnp.float32) * g
        )
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for causal in (False, True):
        mono = grads(False, causal)
        chunk = grads(True, causal)
        for m, c in zip(mono, chunk):
            np.testing.assert_allclose(np.asarray(m), np.asarray(c), rtol=2e-4, atol=2e-4)
    # dropout: counter must be position-keyed, not chunk-local
    mono = grads(False, True, seed=5)
    chunk = grads(True, True, seed=5)
    for m, c in zip(mono, chunk):
        np.testing.assert_allclose(np.asarray(m), np.asarray(c), rtol=2e-4, atol=2e-4)
