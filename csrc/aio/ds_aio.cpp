// Async host I/O engine — the TPU-native DeepNVMe analog.
//
// Role of the reference's libaio stack (csrc/aio/common/deepspeed_aio_common.cpp,
// csrc/aio/py_lib/deepspeed_py_aio_handle.cpp: aio_handle with block_size,
// queue_depth, single_submit, overlap_events, thread_count): saturate a
// local NVMe device with deep-queue async reads/writes of tensor shards so
// ZeRO-Infinity can swap parameter/optimizer state without stalling compute.
//
// Two engines, chosen at handle creation:
//
//  * KERNEL AIO (preferred): Linux native AIO via raw syscalls
//    (io_setup/io_submit/io_getevents — the same interface libaio wraps,
//    no library dependency) over O_DIRECT descriptors.  Requests are cut
//    into block_size chunks, each chunk an iocb against a 512-aligned
//    bounce buffer (posix_memalign; numpy buffers aren't sector-aligned),
//    up to queue_depth in flight.  O_DIRECT bypasses the page cache, so
//    sustained throughput tracks the device, not memcpy-to-cache.
//    Filesystems that reject O_DIRECT (tmpfs) demote the handle to the
//    thread pool at open time.
//  * THREAD POOL (fallback): chunked pread/pwrite fanned across a
//    pthread pool — portable, correct everywhere.
//
// The C ABI below is consumed via ctypes from deepspeed_tpu/ops/aio/aio.py.
#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <linux/aio_abi.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// O_DIRECT transfer granularity.  512 covers most devices; NVMe
// formatted with 4096-byte logical blocks accepts the open but returns
// EINVAL at io_submit — that case demotes to the thread pool at wait()
// (and the engine is marked dead so later requests skip it), or set
// DS_AIO_SECTOR=4096 to keep kernel AIO on such devices.
static int64_t sector_size() {
    static int64_t s = [] {
        const char* e = getenv("DS_AIO_SECTOR");
        long v = e ? atol(e) : 0;
        return (v >= 512 && (v & (v - 1)) == 0) ? v : 512;
    }();
    return s;
}

static long sys_io_setup(unsigned nr, aio_context_t* ctx) { return syscall(SYS_io_setup, nr, ctx); }
static long sys_io_destroy(aio_context_t ctx) { return syscall(SYS_io_destroy, ctx); }
static long sys_io_submit(aio_context_t ctx, long n, struct iocb** ios) {
    return syscall(SYS_io_submit, ctx, n, ios);
}
static long sys_io_getevents(aio_context_t ctx, long min_nr, long nr, struct io_event* ev,
                             struct timespec* ts) {
    return syscall(SYS_io_getevents, ctx, min_nr, nr, ev, ts);
}

static int64_t round_up(int64_t x, int64_t a) { return (x + a - 1) / a * a; }

// ---------------------------------------------------------------------------
// thread-pool engine (portable fallback)
// ---------------------------------------------------------------------------

struct Request {
    int fd = -1;
    char* buf = nullptr;
    int64_t nbytes = 0;
    int64_t file_offset = 0;
    bool is_read = false;
    std::atomic<int64_t> chunks_left{0};
    std::atomic<bool> failed{false};
};

struct Chunk {
    Request* req;
    int64_t offset;  // within the request
    int64_t len;
};

class ThreadPoolEngine {
  public:
    ThreadPoolEngine(int64_t block_size, int thread_count) : block_size_(block_size) {
        int n = thread_count > 0 ? thread_count : 1;
        for (int i = 0; i < n; ++i) workers_.emplace_back([this] { worker(); });
    }

    ~ThreadPoolEngine() {
        {
            std::lock_guard<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
        for (auto* r : inflight_) delete r;
    }

    bool submit(int fd, char* buf, int64_t nbytes, bool is_read, int64_t file_offset) {
        auto* req = new Request();
        req->fd = fd;
        req->buf = buf;
        req->nbytes = nbytes;
        req->file_offset = file_offset;
        req->is_read = is_read;
        int64_t nchunks = (nbytes + block_size_ - 1) / block_size_;
        if (nchunks == 0) nchunks = 1;
        req->chunks_left.store(nchunks);
        {
            std::lock_guard<std::mutex> lk(mu_);
            inflight_.push_back(req);
            for (int64_t c = 0; c < nchunks; ++c) {
                int64_t off = c * block_size_;
                queue_.push_back({req, off, std::min(block_size_, nbytes - off)});
            }
            ++pending_requests_;
        }
        cv_.notify_all();
        return true;
    }

    int64_t wait() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [this] { return pending_requests_ == 0; });
        int64_t n = completed_since_wait_;
        completed_since_wait_ = 0;
        bool ok = true;
        for (auto* r : inflight_) {
            ok = ok && !r->failed.load();
            ::close(r->fd);
            delete r;
        }
        inflight_.clear();
        return ok ? n : -1;
    }

  private:
    void worker() {
        for (;;) {
            Chunk ch;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
                if (stop_ && queue_.empty()) return;
                ch = queue_.front();
                queue_.pop_front();
            }
            Request* r = ch.req;
            int64_t remaining = ch.len;
            int64_t off = ch.offset;
            bool ok = true;
            while (remaining > 0) {
                ssize_t n = r->is_read
                                ? ::pread(r->fd, r->buf + off, remaining, r->file_offset + off)
                                : ::pwrite(r->fd, r->buf + off, remaining, r->file_offset + off);
                if (n <= 0) {
                    ok = false;
                    break;
                }
                off += n;
                remaining -= n;
            }
            if (!ok) r->failed.store(true);
            if (r->chunks_left.fetch_sub(1) == 1) {
                std::lock_guard<std::mutex> lk(mu_);
                --pending_requests_;
                ++completed_since_wait_;
                if (pending_requests_ == 0) done_cv_.notify_all();
            }
        }
    }

    int64_t block_size_;
    std::vector<std::thread> workers_;
    std::deque<Chunk> queue_;
    std::vector<Request*> inflight_;
    std::mutex mu_;
    std::condition_variable cv_, done_cv_;
    int64_t pending_requests_ = 0;
    int64_t completed_since_wait_ = 0;
    bool stop_ = false;
};

// ---------------------------------------------------------------------------
// kernel-AIO engine (O_DIRECT + io_submit deep queues)
// ---------------------------------------------------------------------------

struct AioRequest {
    int fd = -1;
    char* user_buf = nullptr;   // caller's (unaligned) buffer
    char* bounce = nullptr;     // sector-aligned bounce region
    int64_t nbytes = 0;         // true payload size
    int64_t padded = 0;         // sector-rounded size on the wire
    int64_t file_offset = 0;
    bool is_read = false;
    int64_t chunks_left = 0;
    int64_t copied = 0;         // payload bytes actually delivered (reads)
    bool failed = false;
};

struct AioChunk {
    AioRequest* req;
    struct iocb cb;  // PADDED chunk against the bounce buffer
};

class KernelAioEngine {
  public:
    KernelAioEngine(int64_t block_size, int queue_depth)
        : block_size_(round_up(block_size, sector_size())), queue_depth_(queue_depth) {
        ok_ = sys_io_setup(queue_depth_, &ctx_) == 0;
    }

    ~KernelAioEngine() {
        if (ok_) sys_io_destroy(ctx_);
        for (auto* r : inflight_) free_request(r);
    }

    bool available() const { return ok_ && !submit_failed_; }

    // Writes must arrive sector-aligned in length (the handle routes any
    // unaligned tail through the buffered engine — zero-padding a write
    // would clobber pre-existing bytes past the payload); reads may be
    // any length (the bounce copy-back clips to the real payload).
    bool submit(int fd, char* buf, int64_t nbytes, bool is_read, int64_t file_offset) {
        auto* req = new AioRequest();
        req->fd = fd;
        req->user_buf = buf;
        req->nbytes = nbytes;
        req->padded = is_read ? round_up(std::max<int64_t>(nbytes, 1), sector_size()) : nbytes;
        req->file_offset = file_offset;
        req->is_read = is_read;
        if (posix_memalign(reinterpret_cast<void**>(&req->bounce), 4096, req->padded) != 0) {
            delete req;
            return false;
        }
        if (!is_read) std::memcpy(req->bounce, buf, nbytes);
        int64_t nchunks = (req->padded + block_size_ - 1) / block_size_;
        req->chunks_left = nchunks;
        inflight_.push_back(req);
        for (int64_t c = 0; c < nchunks; ++c) {
            int64_t off = c * block_size_;
            int64_t len = std::min(block_size_, req->padded - off);
            // heap-owned: the kernel holds this pointer (aio_data) until
            // the completion event is reaped
            auto* ch = new AioChunk();
            ch->req = req;
            std::memset(&ch->cb, 0, sizeof(ch->cb));
            ch->cb.aio_fildes = fd;
            ch->cb.aio_lio_opcode = is_read ? IOCB_CMD_PREAD : IOCB_CMD_PWRITE;
            ch->cb.aio_buf = reinterpret_cast<uint64_t>(req->bounce + off);
            ch->cb.aio_nbytes = len;
            ch->cb.aio_offset = file_offset + off;
            ch->cb.aio_data = reinterpret_cast<uint64_t>(ch);
            pending_.push_back(ch);
        }
        pump();
        return true;
    }

    // ``failed_out`` (optional): one flag per request in submit order, so
    // the handle can re-run exactly the failed ones through the pool.
    int64_t wait(std::vector<char>* failed_out = nullptr) {
        while (!pending_.empty() || in_kernel_ > 0) {
            pump();
            if (in_kernel_ > 0 && !reap(/*min_nr=*/1)) {
                // io_getevents error with events in flight: tear the
                // context down FIRST — io_destroy cancels/waits the
                // outstanding iocbs, so freeing the bounce buffers
                // below cannot race an in-flight DMA.  The engine is
                // dead afterwards; the handle demotes to the pool.
                sys_io_destroy(ctx_);
                ok_ = false;
                for (auto* r : inflight_) r->failed = true;
                for (auto* ch : pending_) delete ch;
                pending_.clear();
                in_kernel_ = 0;
                break;
            }
        }
        bool ok = true;
        int64_t n = 0;
        if (failed_out) failed_out->clear();
        for (auto* r : inflight_) {
            // a read that could not deliver its full payload is a
            // failure, matching the thread-pool engine's semantics
            bool req_ok = !r->failed && (!r->is_read || r->copied >= r->nbytes);
            ok = ok && req_ok;
            if (failed_out) failed_out->push_back(req_ok ? 0 : 1);
            ::close(r->fd);
            free_request(r);
            ++n;
        }
        inflight_.clear();
        return ok ? n : -1;
    }

  private:
    void free_request(AioRequest* r) {
        std::free(r->bounce);
        delete r;
    }

    // submit as many pending iocbs as the queue allows
    void pump() {
        while (!pending_.empty() && in_kernel_ < queue_depth_) {
            long room = queue_depth_ - in_kernel_;
            std::vector<struct iocb*> batch;
            for (auto it = pending_.begin(); it != pending_.end() && (long)batch.size() < room; ++it)
                batch.push_back(&(*it)->cb);
            long r = sys_io_submit(ctx_, batch.size(), batch.data());
            if (r <= 0) {
                if (in_kernel_ > 0 && reap(1)) continue;  // drain and retry
                // Nothing in flight and the kernel refuses (e.g. EINVAL:
                // 4096-byte-logical-block NVMe rejecting 512-granular
                // iocbs): fail the pending requests and mark the engine
                // unhealthy — the handle re-runs the failed requests
                // through the thread pool at wait() and stops routing
                // here (ADVICE r2: no permanent-failure mode).
                submit_failed_ = true;
                for (auto* ch : pending_) {
                    ch->req->failed = true;
                    delete ch;
                }
                pending_.clear();
                return;
            }
            for (long i = 0; i < r; ++i) pending_.pop_front();
            in_kernel_ += r;
        }
    }

    bool reap(long min_nr) {
        struct io_event events[64];
        long nr = std::min<long>(64, in_kernel_);
        long r;
        do {
            r = sys_io_getevents(ctx_, min_nr, nr, events, nullptr);
        } while (r < 0 && errno == EINTR);  // signals must not fail I/O
        if (r < 0) return false;
        for (long i = 0; i < r; ++i) {
            auto* ch = reinterpret_cast<AioChunk*>(events[i].data);
            AioRequest* req = ch->req;
            if (events[i].res < 0 ||
                (req->is_read ? false : events[i].res != (long long)ch->cb.aio_nbytes))
                req->failed = true;
            if (req->is_read && events[i].res >= 0) {
                // copy only the chunk's real-payload overlap back
                int64_t off = ch->cb.aio_offset - req->file_offset;
                int64_t real = std::min<int64_t>(events[i].res, std::max<int64_t>(req->nbytes - off, 0));
                if (real > 0) std::memcpy(req->user_buf + off, req->bounce + off, real);
                req->copied += std::max<int64_t>(real, 0);
            }
            --req->chunks_left;
            delete ch;
        }
        in_kernel_ -= r;
        return true;
    }

    int64_t block_size_;
    long queue_depth_;
    aio_context_t ctx_ = 0;
    bool ok_ = false;
    bool submit_failed_ = false;
    long in_kernel_ = 0;
    std::deque<AioChunk*> pending_;
    std::vector<AioRequest*> inflight_;
};

// ---------------------------------------------------------------------------
// handle: picks the engine per request (O_DIRECT probe at open)
// ---------------------------------------------------------------------------

class AioHandle {
  public:
    AioHandle(int64_t block_size, int queue_depth, int thread_count)
        : pool_(block_size > 0 ? block_size : (1 << 20), thread_count),
          kaio_(block_size > 0 ? block_size : (1 << 20), queue_depth > 0 ? queue_depth : 32) {
        const char* dis = getenv("DS_AIO_DISABLE_KERNEL_AIO");
        kaio_enabled_ = kaio_.available() && !(dis && dis[0] == '1');
    }

    int64_t submit(const char* path, char* buf, int64_t nbytes, bool is_read, int64_t file_offset) {
        // writes: only the sector-aligned body goes through O_DIRECT; the
        // (<512B) tail rides the buffered pool so no byte past the
        // payload is ever touched.  reads: O_DIRECT end to end (the
        // bounce copy-back clips to the payload).
        int64_t body = is_read ? nbytes : (nbytes / sector_size()) * sector_size();
        if (kaio_enabled_ && file_offset % sector_size() == 0 && body > 0) {
            int flags = (is_read ? O_RDONLY : (O_WRONLY | O_CREAT)) | O_DIRECT;
            int fd = ::open(path, flags, 0644);
            if (fd >= 0) {
                used_kernel_aio_ = true;
                if (!kaio_.submit(fd, buf, body, is_read, file_offset)) {
                    ::close(fd);
                    return -1;
                }
                // record for re-run through the pool if the kernel path
                // fails at io_submit/io_getevents time (wait() below)
                kaio_recs_.push_back(KaioRec{path, buf, body, is_read, file_offset});
                kaio_inflight_ = true;
                if (body == nbytes) {
                    ++user_requests_;
                    return 1;
                }
                // the (<512B) buffered tail must not run CONCURRENTLY
                // with the O_DIRECT body (they can share the file's last
                // page, and mixing direct + page-cache writes to one
                // page is undefined) — defer it until wait() has
                // completed the body
                int tfd = ::open(path, O_WRONLY | O_CREAT, 0644);
                if (tfd < 0) return -1;
                tails_.push_back(PendingTail{tfd, buf + body, nbytes - body, file_offset + body});
                ++user_requests_;  // body+tail are ONE user request
                return 1;
            }
            // EINVAL etc: filesystem rejects O_DIRECT — fall through
        }
        int fd = ::open(path, is_read ? O_RDONLY : (O_WRONLY | O_CREAT), 0644);
        if (fd < 0) return -1;
        pool_inflight_ = true;
        if (!pool_.submit(fd, buf, nbytes, is_read, file_offset)) return -1;
        ++user_requests_;
        return 1;
    }

    int64_t wait() {
        bool ok = true;
        if (kaio_inflight_) {
            std::vector<char> failed;
            bool kaio_ok = kaio_.wait(&failed) >= 0;
            kaio_inflight_ = false;
            if (!kaio_.available()) kaio_enabled_ = false;  // engine unhealthy
            if (!kaio_ok) {
                // Re-run exactly the failed requests through the thread
                // pool (fresh buffered fds).  Safe for both directions:
                // a repeated read refills the same caller buffer, a
                // repeated write rewrites the same payload bytes.
                bool requeued_all = true;
                for (size_t i = 0; i < kaio_recs_.size() && i < failed.size(); ++i) {
                    if (!failed[i]) continue;
                    const KaioRec& rec = kaio_recs_[i];
                    int fd = ::open(rec.path.c_str(),
                                    rec.is_read ? O_RDONLY : (O_WRONLY | O_CREAT), 0644);
                    if (fd < 0 || !pool_.submit(fd, rec.buf, rec.nbytes, rec.is_read, rec.off)) {
                        if (fd >= 0) ::close(fd);
                        requeued_all = false;
                        continue;
                    }
                    pool_inflight_ = true;
                }
                ok = ok && requeued_all;
            }
            kaio_recs_.clear();
        }
        if (pool_inflight_) {
            ok = ok && pool_.wait() >= 0;
            pool_inflight_ = false;
        }
        for (auto& t : tails_) {  // ordered strictly after the bodies
            int64_t done = 0;
            while (done < t.len) {
                ssize_t w = ::pwrite(t.fd, t.buf + done, t.len - done, t.off + done);
                if (w <= 0) {
                    ok = false;
                    break;
                }
                done += w;
            }
            ::close(t.fd);
        }
        tails_.clear();
        int64_t n = user_requests_;
        user_requests_ = 0;
        return ok ? n : -1;
    }

    int used_kernel_aio() const { return used_kernel_aio_ ? 1 : 0; }

  private:
    struct PendingTail {
        int fd;
        const char* buf;
        int64_t len;
        int64_t off;
    };

    struct KaioRec {  // enough to replay a request through the pool
        std::string path;
        char* buf;
        int64_t nbytes;
        bool is_read;
        int64_t off;
    };

    ThreadPoolEngine pool_;
    KernelAioEngine kaio_;
    std::vector<PendingTail> tails_;
    std::vector<KaioRec> kaio_recs_;
    bool kaio_enabled_ = false;
    bool kaio_inflight_ = false;
    bool pool_inflight_ = false;
    bool used_kernel_aio_ = false;
    int64_t user_requests_ = 0;
};

}  // namespace

extern "C" {

void* ds_aio_create(int64_t block_size, int queue_depth, int single_submit,
                    int overlap_events, int thread_count) {
    (void)single_submit;  // submission batching is implicit in the chunk queue
    (void)overlap_events;
    return new AioHandle(block_size, queue_depth, thread_count);
}

void ds_aio_destroy(void* h) { delete static_cast<AioHandle*>(h); }

int64_t ds_aio_pread(void* h, char* buf, int64_t nbytes, const char* path, int64_t file_offset) {
    return static_cast<AioHandle*>(h)->submit(path, buf, nbytes, /*is_read=*/true, file_offset);
}

int64_t ds_aio_pwrite(void* h, const char* buf, int64_t nbytes, const char* path, int64_t file_offset) {
    return static_cast<AioHandle*>(h)->submit(path, const_cast<char*>(buf), nbytes,
                                              /*is_read=*/false, file_offset);
}

int64_t ds_aio_wait(void* h) { return static_cast<AioHandle*>(h)->wait(); }

int ds_aio_used_kernel_aio(void* h) { return static_cast<AioHandle*>(h)->used_kernel_aio(); }

}  // extern "C"
