// Async host I/O engine — the TPU-native DeepNVMe analog.
//
// Role of the reference's libaio stack (csrc/aio/common/deepspeed_aio_common.cpp,
// csrc/aio/py_lib/deepspeed_py_aio_handle.cpp: aio_handle with block_size,
// queue_depth, single_submit, overlap_events, thread_count): saturate a
// local NVMe device with deep-queue async reads/writes of tensor shards so
// ZeRO-Infinity can swap parameter/optimizer state without stalling compute.
//
// This implementation gets its queue depth from a pthread pool doing
// chunked pread/pwrite on O_DIRECT-less descriptors (portable; the
// per-chunk fan-out across threads is what produces the parallel QD the
// reference gets from io_submit).  Chunk size = block_size; a request is
// split into chunks, chunks are claimed by workers, and a per-request
// atomic counter signals completion.  The C ABI below is consumed via
// ctypes from deepspeed_tpu/ops/aio/aio.py.
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
    int fd = -1;
    char* buf = nullptr;
    int64_t nbytes = 0;
    int64_t file_offset = 0;
    bool is_read = false;
    std::atomic<int64_t> chunks_left{0};
    std::atomic<int64_t> bytes_done{0};
    std::atomic<bool> failed{false};
};

struct Chunk {
    Request* req;
    int64_t offset;  // within the request
    int64_t len;
};

class AioHandle {
  public:
    AioHandle(int64_t block_size, int queue_depth, int thread_count)
        : block_size_(block_size > 0 ? block_size : (1 << 20)),
          queue_depth_(queue_depth > 0 ? queue_depth : 8) {
        int n = thread_count > 0 ? thread_count : 1;
        for (int i = 0; i < n; ++i) workers_.emplace_back([this] { worker(); });
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
        for (auto* r : inflight_) delete r;
    }

    // returns request id >= 0, or -1 on open failure
    int64_t submit(const char* path, char* buf, int64_t nbytes, bool is_read, int64_t file_offset) {
        int flags = is_read ? O_RDONLY : (O_WRONLY | O_CREAT);
        int fd = ::open(path, flags, 0644);
        if (fd < 0) return -1;
        auto* req = new Request();
        req->fd = fd;
        req->buf = buf;
        req->nbytes = nbytes;
        req->file_offset = file_offset;
        req->is_read = is_read;
        int64_t nchunks = (nbytes + block_size_ - 1) / block_size_;
        if (nchunks == 0) nchunks = 1;
        req->chunks_left.store(nchunks);
        {
            std::lock_guard<std::mutex> lk(mu_);
            inflight_.push_back(req);
            for (int64_t c = 0; c < nchunks; ++c) {
                int64_t off = c * block_size_;
                queue_.push_back({req, off, std::min(block_size_, nbytes - off)});
            }
            ++pending_requests_;
        }
        cv_.notify_all();
        return 1;
    }

    // block until every submitted request completes; returns number of
    // requests completed since the last wait, or -1 if any failed
    int64_t wait() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [this] { return pending_requests_ == 0; });
        int64_t n = completed_since_wait_;
        completed_since_wait_ = 0;
        bool ok = true;
        for (auto* r : inflight_) {
            ok = ok && !r->failed.load();
            ::close(r->fd);
            delete r;
        }
        inflight_.clear();
        return ok ? n : -1;
    }

  private:
    void worker() {
        for (;;) {
            Chunk ch;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
                if (stop_ && queue_.empty()) return;
                ch = queue_.front();
                queue_.pop_front();
            }
            Request* r = ch.req;
            int64_t remaining = ch.len;
            int64_t off = ch.offset;
            bool ok = true;
            while (remaining > 0) {
                ssize_t n = r->is_read
                                ? ::pread(r->fd, r->buf + off, remaining, r->file_offset + off)
                                : ::pwrite(r->fd, r->buf + off, remaining, r->file_offset + off);
                if (n <= 0) {
                    ok = false;
                    break;
                }
                off += n;
                remaining -= n;
            }
            if (!ok) r->failed.store(true);
            r->bytes_done.fetch_add(ch.len - remaining);
            if (r->chunks_left.fetch_sub(1) == 1) {
                std::lock_guard<std::mutex> lk(mu_);
                --pending_requests_;
                ++completed_since_wait_;
                if (pending_requests_ == 0) done_cv_.notify_all();
            }
        }
    }

    int64_t block_size_;
    int queue_depth_;
    std::vector<std::thread> workers_;
    std::deque<Chunk> queue_;
    std::vector<Request*> inflight_;
    std::mutex mu_;
    std::condition_variable cv_, done_cv_;
    int64_t pending_requests_ = 0;
    int64_t completed_since_wait_ = 0;
    bool stop_ = false;
};

}  // namespace

extern "C" {

void* ds_aio_create(int64_t block_size, int queue_depth, int single_submit,
                    int overlap_events, int thread_count) {
    (void)single_submit;  // submission batching is implicit in the chunk queue
    (void)overlap_events;
    return new AioHandle(block_size, queue_depth, thread_count);
}

void ds_aio_destroy(void* h) { delete static_cast<AioHandle*>(h); }

int64_t ds_aio_pread(void* h, char* buf, int64_t nbytes, const char* path, int64_t file_offset) {
    return static_cast<AioHandle*>(h)->submit(path, buf, nbytes, /*is_read=*/true, file_offset);
}

int64_t ds_aio_pwrite(void* h, const char* buf, int64_t nbytes, const char* path, int64_t file_offset) {
    return static_cast<AioHandle*>(h)->submit(path, const_cast<char*>(buf), nbytes,
                                              /*is_read=*/false, file_offset);
}

int64_t ds_aio_wait(void* h) { return static_cast<AioHandle*>(h)->wait(); }

}  // extern "C"
