// Host SIMD Adam — the ZeRO-Offload optimizer kernel.
//
// Role of the reference's csrc/adam/cpu_adam.cpp (AVX256/AVX512 paths in
// csrc/includes/cpu_adam.h:28-139, OpenMP-parallel): run the fp32
// optimizer update on host-resident shards so device memory holds only
// bf16 params + activations.  Here the SIMD comes from -O3 -march=native
// auto-vectorization over flat contiguous arrays (the loop below compiles
// to packed FMA on AVX2/AVX-512 hosts) with OpenMP across cores; the
// C ABI is consumed by ctypes from deepspeed_tpu/ops/adam/cpu_adam.py.
#include <cmath>
#include <cstdint>

extern "C" {

// Flat fused Adam/AdamW step over contiguous fp32 buffers.
//   params/grads/exp_avg/exp_avg_sq: length n
//   step: 1-based step count (bias correction)
//   adamw: 1 = decoupled weight decay (AdamW), 0 = L2-style (classic)
void ds_cpu_adam_step(float* params, const float* grads, float* exp_avg,
                      float* exp_avg_sq, int64_t n, float lr, float beta1,
                      float beta2, float eps, float weight_decay, int64_t step,
                      int adamw) {
    const float bc1 = 1.0f - std::pow(beta1, (float)step);
    const float bc2 = 1.0f - std::pow(beta2, (float)step);
    const float step_size = lr / bc1;
    const float inv_sqrt_bc2 = 1.0f / std::sqrt(bc2);
    const float b1 = beta1, b2 = beta2;
    const float omb1 = 1.0f - beta1, omb2 = 1.0f - beta2;
    const float decay = weight_decay;

#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        if (!adamw && decay > 0.0f) g += decay * p;  // classic L2
        float m = b1 * exp_avg[i] + omb1 * g;
        float v = b2 * exp_avg_sq[i] + omb2 * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float denom = std::sqrt(v) * inv_sqrt_bc2 + eps;
        float update = step_size * (m / denom);  // lr/bc1 folds bias corr.
        if (adamw && decay > 0.0f) update += lr * decay * p;  // decoupled, plain lr
        params[i] = p - update;
    }
}

// Fused momentum-SGD for completeness (host path for the SGD optimizer).
void ds_cpu_sgd_step(float* params, const float* grads, float* momentum_buf,
                     int64_t n, float lr, float momentum, float weight_decay) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i] + weight_decay * params[i];
        if (momentum > 0.0f) {
            float m = momentum * momentum_buf[i] + g;
            momentum_buf[i] = m;
            g = m;
        }
        params[i] -= lr * g;
    }
}

// Cast fp32 host buffer -> bf16 (round-to-nearest-even) for the
// device-bound copy after the host step (the reference overlaps an H2D
// fp16 copy-back, cpu_adam.cpp param_copy path).
void ds_fp32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits;
        __builtin_memcpy(&bits, &src[i], 4);
        uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
        dst[i] = (uint16_t)((bits + rounding) >> 16);
    }
}

}  // extern "C"
