"""Bench-history diff + the perf-sentinel CI gate.

Reads ``bench_history.jsonl`` (written by ``bench.py`` and the
standalone ``tools/bench_*.py`` sweeps), computes noise-aware deltas —
newest run vs the median of the prior window per (metric, backend,
config-fingerprint) key, thresholds widened by the window's own MAD —
and prints one verdict row per key.

Exit status (``--gate``): 0 when no key regresses, 1 on any ``regress``
verdict — the ``perf-sentinel`` CI job runs exactly this.

Blessing an intentional change: ``bench_diff.py --bless '<metric>|*'
--note 'why'`` appends a marker; diffs ignore history before the last
applicable marker, so the new normal becomes the baseline instead of a
permanent red (docs/performance.md §Regression workflow).

Run:
  python tools/bench_diff.py                 # verdict table
  python tools/bench_diff.py --gate          # CI gate (exit 1 on regress)
  python tools/bench_diff.py --bless '*' --note 'flash kernel rewrite'
  python tools/bench_diff.py --json out.json # machine-readable verdicts
"""
import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_regression():
    # file-path import: keeps this CLI jax-free (usable on a bare CI
    # runner and inside the jax-free bench.py parent's environment)
    path = os.path.join(HERE, "deepspeed_tpu", "telemetry", "regression.py")
    spec = importlib.util.spec_from_file_location("_ds_bench_regression", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    reg = _load_regression()
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--history", default=os.path.join(HERE, "bench_history.jsonl"))
    p.add_argument("--window", type=int, default=8,
                   help="baseline = median of up to N prior runs")
    p.add_argument("--threshold", type=float, default=0.05,
                   help="default relative regression threshold")
    p.add_argument("--thresholds", default="",
                   help="per-metric overrides: 'substr:0.08,substr2:0.03'")
    p.add_argument("--band-cap", type=float, default=None,
                   help="upper bound on the MAD-widened noise band (CI red check)")
    p.add_argument("--metric", action="append", default=None,
                   help="restrict to these metric names (repeatable)")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 on any regress verdict (CI perf-sentinel)")
    p.add_argument("--json", default="", help="also write verdicts as JSON")
    p.add_argument("--bless", default="",
                   help="record an intentional change for METRIC ('*' = all) and exit")
    p.add_argument("--note", default="", help="why the bless is justified")
    args = p.parse_args(argv)

    if args.bless:
        marker = reg.history_bless(args.bless, note=args.note, path=args.history)
        print(f"blessed {marker['metric']!r} at {marker['git_sha']}"
              + (f": {args.note}" if args.note else ""))
        return 0

    thresholds = {}
    for part in (s for s in args.thresholds.split(",") if s):
        pat, _, th = part.rpartition(":")
        thresholds[pat] = float(th)

    history = reg.history_load(args.history)
    if not history:
        print(f"no bench history at {args.history} — nothing to diff")
        # a gate with no input stream must fail loudly: a silently
        # broken history writer would otherwise gate green forever
        return 1 if args.gate else 0
    verdicts = reg.bench_diff(
        history, window=args.window, default_threshold=args.threshold,
        thresholds=thresholds, metrics=args.metric, band_cap=args.band_cap,
    )
    print(reg.format_verdicts(verdicts))
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"schema": 1, "verdicts": verdicts}, f, indent=1)
    ok, bad = reg.gate(verdicts)
    if not ok:
        print(f"\nREGRESSION: {len(bad)} metric(s) past their noise band", file=sys.stderr)
        for v in bad:
            print(
                f"  {v['metric']} [{v['backend']}]: {v['value']:.1f} vs baseline "
                f"{v['baseline']:.1f} ({v['delta_pct']:+.1f}%, band {v['band_pct']:.1f}%)",
                file=sys.stderr,
            )
        if args.gate:
            return 1
    elif args.gate:
        n = sum(1 for v in verdicts if v["verdict"] != "no-baseline")
        print(f"\ngate OK: {n} baselined metric(s), no regressions")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
