"""Chip-implied MFU of the GPT-2 XL (1.5B) streaming train step.

The steady-state XL streaming record (tools/train_xl_onchip.py) is
bound by the dev tunnel's ~10 MB/s host link — its wall time says
nothing about the CHIP.  This tool measures what the chip itself does:
each compiled stage program of the ZeRO-Infinity executor (group fwd,
group vjp, embed, head+vjp, embed bwd) is timed ON DEVICE by the
marginal two-length method: jitted ``lax.scan`` chains of ``iters``
and ``4*iters`` iterations returning scalars, per-iteration time =
(wall_4n − wall_n)/(3n), so the tunnel's variable dispatch+readback
RTT (and any activation-fetch cost) cancels.  Every
chain's per-iteration input GENUINELY depends on the carry — either
the previous iteration's output feeds the next (group chains) or the
input is gated by ``where(pred(carry), x, zeros)``, which XLA cannot
simplify away (identical-branch selects could be, and were — review
finding r5); so no stage is loop-invariant-hoistable.

    chip_step_s = G*(t_group_fwd + t_group_bwd) + t_embed + t_head + t_embed_bwd
    chip_mfu    = step_flops / (chip_step_s * peak_flops)

This is the number a real deployment (PCIe-class host link, or fsdp
over multiple hosts) converges to as the upload pipeline stops being
the bottleneck — the VERDICT r4 "missing #3" evidence row.

Run: python tools/xl_chip_mfu.py [seq] [micro_bs] [buffer_count] [iters]
"""
import json
import os
import sys
import functools
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import bench
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    seq = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    mb = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    lpg = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    iters = int(sys.argv[4]) if len(sys.argv) > 4 else 16

    cfg = gpt2.GPT2_XL
    model_fn, init_fn, _ = gpt2.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": mb,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "cpu", "buffer_count": lpg},
            "offload_optimizer": {"device": "cpu"},
        },
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    t0 = time.time()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config
    )
    print(f"init {time.time() - t0:.0f}s  groups={engine.n_groups}", flush=True)
    spec = engine.spec
    G = engine.n_groups
    n = iters

    rng = np.random.default_rng(0)
    tokens_np = rng.integers(0, cfg.vocab_size, (mb, seq), dtype=np.int32)
    res = engine._upload_resident()
    g0 = engine._upload_group(0)
    mbatch = {"input_ids": jax.device_put(tokens_np, engine._batch_sh)}
    tokens = mbatch["input_ids"]
    rngs = engine._layer_rngs(0, 0)[0]

    def sync(x):
        # block_until_ready is unreliable through the tunnel; pull bytes
        np.asarray(jax.device_get(jax.tree.leaves(x)[0]))

    def timed(fn, *args):
        """Marginal two-length timing: wall(4n-chain) − wall(n-chain)
        = 3n iterations of pure chip time — the tunnel's dispatch+
        readback RTT (tens to hundreds of ms, variable) cancels instead
        of inflating every per-program number by RTT/n (the r5.0 single
        -chain numbers carried that artifact).  Chains return SCALARS
        (a full activation fetch is ~0.3s on the 10 MB/s link — more
        than the chain itself); the 3n span + best-of-5 per length
        keeps residual RTT jitter well under the measured delta."""
        sync(fn(n, *args))  # compile + warm (n)
        sync(fn(4 * n, *args))  # compile + warm (4n)

        def best(length):
            b = float("inf")
            for _ in range(5):
                t0 = time.time()
                sync(fn(length, *args))
                b = min(b, time.time() - t0)
            return b

        delta = best(4 * n) - best(n)
        if delta <= 0:
            # publishing a record with a zeroed stage would silently
            # inflate the MFU — abort instead
            raise SystemExit(
                f"non-positive marginal delta {delta:.4f}s — RTT jitter "
                "exceeds the chain span; re-run with a larger iters"
            )
        return delta / (3 * n)

    def gate(pred_scalar, x):
        """where(pred, x, 0): carry-dependent and NOT simplifiable (the
        compiler cannot prove pred) — the hoist-blocker for chains whose
        natural input is loop-invariant."""
        return jnp.where(pred_scalar, x, jnp.zeros_like(x))

    @functools.partial(jax.jit, static_argnums=0)
    def chain_group_fwd(length, gp, x, r):
        # output feeds the next iteration: naturally carry-dependent;
        # scalar result — fetching a full activation would dominate wall
        def body(x_, _):
            return spec.group(gp, x_, r, spec.deterministic), None

        y, _ = jax.lax.scan(body, x, None, length=length)
        return jnp.mean(y.astype(jnp.float32))

    @functools.partial(jax.jit, static_argnums=0)
    def chain_group_bwd(length, gp, x, r, dy):
        # cotangent chains through dx: naturally carry-dependent
        def body(dy_, _):
            _, vjp = jax.vjp(lambda g_, x_: spec.group(g_, x_, r, spec.deterministic), gp, x)
            dgp, dx = vjp(dy_)
            return dx.astype(dy_.dtype), None

        out, _ = jax.lax.scan(body, dy, None, length=length)
        return jnp.mean(out.astype(jnp.float32))

    @functools.partial(jax.jit, static_argnums=0)
    def chain_embed(length, r_, t_):
        def body(c, _):
            y = spec.embed(r_, gate(jnp.isfinite(c), t_.astype(jnp.float32)).astype(t_.dtype))
            return y.astype(jnp.float32).reshape(-1)[0], None

        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=length)
        return c

    @functools.partial(jax.jit, static_argnums=0)
    def chain_head(length, r_, x_):
        def body(c, _):
            def f(rr, xx):
                return spec.head_loss(rr, xx, mbatch)

            loss, vjp = jax.vjp(f, r_, gate(jnp.isfinite(c), x_))
            d_res, dx = vjp(jnp.float32(1.0).astype(loss.dtype))
            return loss.astype(jnp.float32) + dx.astype(jnp.float32).reshape(-1)[0], None

        y, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=length)
        return y

    @functools.partial(jax.jit, static_argnums=0)
    def chain_embed_bwd(length, r_, t_, dx0):
        def body(c, _):
            _, vjp = jax.vjp(lambda rr: spec.embed(rr, t_), r_)
            (d_res,) = vjp(gate(jnp.isfinite(c), dx0))
            return jax.tree.leaves(d_res)[0].astype(jnp.float32).reshape(-1)[0], None

        y, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=length)
        return y

    x0 = jax.jit(lambda r_, t_: spec.embed(r_, t_))(res, tokens)
    y0 = jax.jit(lambda gp, x, r: spec.group(gp, x, r, spec.deterministic))(g0, x0, rngs)
    dy = jnp.ones_like(y0)  # cotangent in the GROUP's output dtype
    t_gf = timed(chain_group_fwd, g0, y0, rngs)
    t_gb = timed(chain_group_bwd, g0, x0, rngs, dy)
    t_em = timed(chain_embed, res, tokens)
    t_hd = timed(chain_head, res, x0)
    t_eb = timed(chain_embed_bwd, res, tokens, jnp.ones_like(x0))
    print(
        f"per-program chip times: group_fwd={t_gf * 1000:.1f}ms "
        f"group_bwd={t_gb * 1000:.1f}ms embed={t_em * 1000:.1f}ms "
        f"head(+vjp)={t_hd * 1000:.1f}ms embed_bwd={t_eb * 1000:.1f}ms",
        flush=True,
    )

    chip_step = G * (t_gf + t_gb) + t_em + t_hd + t_eb
    n_params = cfg.num_params()
    tokens_per_step = mb * seq
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * seq
    peak = bench.peak_flops_per_chip(jax.default_backend())
    chip_mfu = tokens_per_step * flops_per_token / chip_step / peak

    rec = {
        "metric": "gpt2_xl_1p5b_streaming_chip_mfu",
        "value": round(chip_mfu * 100, 2),
        "unit": "percent_of_peak",
        "chip_seconds_per_step": round(chip_step, 4),
        "per_program_ms": {
            "group_fwd": round(t_gf * 1e3, 1),
            "group_bwd": round(t_gb * 1e3, 1),
            "embed": round(t_em * 1e3, 1),
            "head_vjp": round(t_hd * 1e3, 1),
            "embed_bwd": round(t_eb * 1e3, 1),
            "n_groups": G,
        },
        "seq": seq,
        "micro_bs": mb,
        "iters": iters,
        "method": (
            "marginal two-length chained timing: each stage program runs "
            f"as a jitted lax.scan chain of {iters} and {4 * iters} "
            "iterations returning a SCALAR (best-of-5 each); "
            "per-iteration chip time = (wall_4n - wall_n)/(3n), so the "
            "tunnel's variable dispatch+readback RTT cancels (r5.0 "
            "single-chain numbers carried RTT/n inflation and fetched "
            "full activations). Every chain's input depends on "
            "its carry (group chains feed outputs forward; fixed-input "
            "chains gate through where(pred(carry), x, 0)), so nothing "
            "is loop-invariant-hoistable. chip_step = G*(fwd+vjp) + "
            "embed + head + embed_bwd; MFU = step_flops/(chip_step*"
            "peak). Tunnel-bound phases (group upload over the ~10MB/s "
            "dev link, grad drain, host Adam) are excluded by "
            "construction — they pipeline under compute on a PCIe-class "
            "host link."
        ),
    }
    print("RESULT " + json.dumps(rec), flush=True)
    bench.append_capability_record(rec)


if __name__ == "__main__":
    main()
