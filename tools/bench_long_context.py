"""Long-context TRAINING throughput: sparse (BigBird splash) vs dense
flash attention inside the full engine train step at 8k/16k sequence —
the reference's long-sequence story is block-sparse attention
("10x longer sequences, up to 6.3x faster",
docs/_posts/2020-09-09-sparse-attention.md:27-33); this measures the
TPU-native analog end-to-end (not just the attention kernel): GPT-2
small-width (768) model, selective remat keeping the attention kernels'
residuals (attn_o/attn_lse — both the flash and splash paths emit
them), chunked cross-entropy, in-kernel dropout available.

Run: python tools/bench_long_context.py [seq] [n_layer]
Appends a capability record on TPU.
"""
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_mode(mode: str, seq: int, n_layer: int, steps: int):
    import jax

    import bench
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    on_tpu = jax.default_backend() in ("tpu", "axon")
    cfg = dataclasses.replace(
        gpt2.GPT2_SMALL if on_tpu else gpt2.GPT2_TINY,
        n_positions=seq,
        n_layer=n_layer,
        attention_mode=mode,
        remat=True,
        xent_chunk_size=512,
        remat_save_names=("qkv", "attn_o", "attn_lse"),
    )
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    # The fed global batch is (dp, seq).  Pin the config to it (mesh
    # default data=-1 makes dp == device count) so the engine's batch
    # triad check holds by construction and the per-chip tokens/s
    # normalization below (seq/dt — the dp-sized batch cancels the dp
    # chips) can't silently drift if either side changes.
    dp_devices = jax.device_count()
    config = {
        "train_batch_size": dp_devices,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    params = gpt2.init_params_device(cfg) if on_tpu else init_fn()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=params, config=config, tp_spec_fn=tp_fn
    )
    rng = np.random.default_rng(0)

    # global batch = dp world (1 on the single TPU chip; the 8-CPU dev
    # mesh shards one sample per device — tokens/s stays per-chip)
    dp = engine.mesh_info.dp_world_size
    assert dp == dp_devices, (
        f"mesh dp world ({dp}) != device count ({dp_devices}); the config "
        "batch above was pinned to the wrong dp"
    )
    def batches(n):
        for _ in range(n):
            yield {"input_ids": rng.integers(0, cfg.vocab_size, (dp, seq), dtype=np.int32)}

    dt, _phases = bench._timed_steps(engine, batches, steps, f"long-{mode}-{seq}")
    tok_s = seq / dt  # per-chip: the dp-sized global batch cancels the dp chips
    print(f"[long-context {mode}] seq={seq} L={n_layer}: step={dt*1e3:.1f}ms tokens/s={tok_s:,.0f}", flush=True)
    return dt, tok_s


def make_record(seq: int, n_layer: int, dt_f: float, tok_f: float, dt_s: float, tok_s: float) -> dict:
    """The capability/bench record for one sparse-vs-dense pair — single
    source of the metric name and field layout (bench.py's longctx-train
    rung and this tool's main() both emit it)."""
    speedup = dt_f / dt_s
    return {
        "metric": f"long_context_seq{seq}_sparse_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s (full train step, 1 chip)",
        "dense_flash_tokens_per_sec": round(tok_f, 1),
        "sparse_over_dense": round(speedup, 2),
        "n_layer": n_layer,
        "note": "end-to-end TRAINING step (fwd+bwd+Adam) with BigBird splash "
        "attention vs dense flash; selective remat keeps both kernels' "
        "attn_o/attn_lse residuals (reference long-seq claim: up to 6.3x, "
        "sparse-attention blog :32; NB r5.1 made the DENSE baseline itself "
        "2.19x faster at 16k via splash-dense routing)",
    }


def main():
    import jax

    seq = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    n_layer = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if not on_tpu:
        # big enough for the default BigBird layout's sliding window
        seq, n_layer = min(seq, 512), 2
    steps = 4 if on_tpu else 2

    dt_f, tok_f = run_mode("flash", seq, n_layer, steps)
    dt_s, tok_s = run_mode("sparse", seq, n_layer, steps)
    print(f"sparse speedup over dense flash at seq {seq}: {dt_f / dt_s:.2f}x", flush=True)

    rec = make_record(seq, n_layer, dt_f, tok_f, dt_s, tok_s)
    print("RESULT " + json.dumps(rec), flush=True)
    from deepspeed_tpu.telemetry.regression import tool_history_emit

    tool_history_emit(rec, rung="longctx-train",
                      base_dir=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if on_tpu:
        import bench

        bench.append_capability_record(rec)


if __name__ == "__main__":
    main()
