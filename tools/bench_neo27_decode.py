"""GPT-Neo-2.7B-class KV-cache decode on one chip — the BASELINE.json
workload ladder's last rung ("GPT-Neo-2.7B inference with kernel
injection").  HF GPT-Neo weights flow through
``inference/injection.HFGPTNEOLayerPolicy`` (HF-parity test in
tests/test_inference.py); this probe measures serving throughput at the
2.7B scale with an on-chip random init (bf16 weights ≈ 5.3GB HBM) and
appends the record to BENCH_CAPABILITY.json.

The measurement itself is ``bench.bench_inference`` — identical
methodology (windowed marginal decode rate + noise guard) to the XL
decode rungs, applied to the Neo preset.

Run: python tools/bench_neo27_decode.py [quantize_bits: 0|8]
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    import bench
    from deepspeed_tpu.models import gpt2

    bits = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    label = {0: "bf16", 8: "int8"}.get(bits)
    if label is None:
        raise SystemExit("quantize_bits must be 0 (bf16) or 8 (true-int8 serving)")
    on_tpu = jax.default_backend() in ("tpu", "axon")
    name = "gpt-neo-2.7b" if on_tpu else "tiny"  # dev runs shrink the model

    rec = bench.bench_inference(name, bits, label)
    rec.update(
        params_m=round(gpt2.PRESETS[name].num_params() / 1e6, 1),
        note="BASELINE ladder final rung: 2.7B-class serving on one v5e; "
        "HF GPT-Neo weights map through HFGPTNEOLayerPolicy (parity test "
        "in tests/test_inference.py); random on-chip init",
    )
    print("RESULT " + json.dumps(rec), flush=True)
    if on_tpu:
        bench.append_capability_record(rec)


if __name__ == "__main__":
    main()
