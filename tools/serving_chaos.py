"""Serving chaos smoke: a REAL ``kill -9`` mid-decode, then restart +
journal replay, asserted bit-identical to an uninterrupted run.

The in-process form of this proof (``InjectedKill``) lives in
tests/test_serving_resilience.py; this tool runs the real thing for the
``serving-chaos`` CI job: the victim child is SIGKILL'd by a seeded
``DS_FAULT_PLAN`` (no Python unwinding, no atexit — exactly what a
hardware loss looks like), and a second child recovers from the journal
the victim's acknowledged submits committed into.

    python tools/serving_chaos.py --dryrun        # tiny model, CPU

Roles (children are re-invocations of this file):

* ``victim``   — submit the seeded workload, serve until the fault plan
  kills the process at its Nth decode dispatch;
* ``recover``  — fresh engine over the victim's journal: ``recover()``
  then drain, print the replayed ids + outputs as JSON;
* ``reference``— uninterrupted run of the same workload (fresh journal),
  print every output.

The parent asserts: the victim died to SIGKILL, the recover child
replayed exactly the incomplete set, and every replayed output equals
the reference's (greedy AND seeded-sampling requests) — then emits one
bench-style JSON record.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

if "--dryrun" in sys.argv or os.environ.get("JAX_PLATFORMS") is None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KILL_AFTER_DECODES = 4
N_REQUESTS = 6
MAX_NEW = 5


def log(msg):
    print(f"[serving_chaos] {msg}", file=sys.stderr, flush=True)


def build_workload(seed, vocab):
    import numpy as np

    rng = np.random.default_rng(seed)
    out = []
    for i in range(N_REQUESTS):
        out.append({
            "prompt": rng.integers(1, vocab, int(rng.integers(4, 20)), dtype=np.int32),
            "max_new": MAX_NEW,
            # one seeded-sampling request proves replay reproduces
            # sampled tokens too (keys are fold_in(seed, position))
            "sample": i == 2,
        })
    return out


def make_engine(journal_dir, seed):
    import dataclasses

    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.serving import ServingEngine

    cfg = dataclasses.replace(gpt2.GPT2_TINY, remat=False)
    params = gpt2.init_params(cfg, seed=7)
    params["wpe"] = params["wpe"] * 40.0
    eng = deepspeed_tpu.init_inference(
        model_config=cfg, params=params, dtype=jnp.float32,
        max_out_tokens=cfg.n_positions,
    )
    srv = ServingEngine(
        eng, num_slots=2, prefill_chunk=8, max_len=64, journal_dir=journal_dir,
    )
    return cfg, srv


def submit_all(srv, workload):
    rids = []
    for w in workload:
        kw = (
            dict(do_sample=True, temperature=0.9, top_k=8, seed=123)
            if w["sample"] else {}
        )
        rids.append(srv.submit(w["prompt"], max_new_tokens=w["max_new"], **kw))
    return rids


def run_child(role, seed):
    from deepspeed_tpu.resilience import faults

    journal_dir = os.environ["DS_CHAOS_JOURNAL"]
    cfg, srv = make_engine(journal_dir, seed)
    workload = build_workload(seed, cfg.vocab_size)
    if role == "victim":
        faults.install_from_env(rank=0)
        submit_all(srv, workload)
        srv.drain(max_steps=2000)
        log("victim was NOT killed — fault plan did not fire")
        sys.exit(3)
    replayed = []
    if role == "recover":
        replayed = srv.recover()
    else:  # reference
        submit_all(srv, workload)
    res = srv.drain(max_steps=2000)
    print(json.dumps({
        "replayed": replayed,
        "outputs": {str(rid): [int(t) for t in r.tokens()] for rid, r in res.items()},
    }), flush=True)


def spawn(role, journal_dir, seed, fault_plan=None):
    env = dict(os.environ, DS_CHAOS_JOURNAL=journal_dir)
    env.pop("DS_FAULT_PLAN", None)
    if fault_plan is not None:
        env["DS_FAULT_PLAN"] = fault_plan
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--role", role,
         "--seed", str(seed), "--dryrun"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    return p


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dryrun", action="store_true", help="tiny model on CPU")
    ap.add_argument("--role", default=None, choices=(None, "victim", "recover", "reference"))
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.role is not None:
        run_child(args.role, args.seed)
        return

    from deepspeed_tpu.resilience.faults import plan_json

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="serving_chaos_") as root:
        victim_journal = os.path.join(root, "journal")
        ref_journal = os.path.join(root, "journal_ref")
        plan = plan_json([
            {"site": "serving.decode", "action": "sigkill",
             "after": KILL_AFTER_DECODES},
        ])

        log(f"victim: seeded SIGKILL at decode dispatch {KILL_AFTER_DECODES + 1}")
        v = spawn("victim", victim_journal, args.seed, fault_plan=plan)
        if v.returncode != -signal.SIGKILL:
            log(f"victim exited {v.returncode}, expected {-signal.SIGKILL}\n{v.stderr}")
            sys.exit(1)
        log(f"victim died to SIGKILL as planned (rc={v.returncode})")

        r = spawn("recover", victim_journal, args.seed)
        if r.returncode != 0:
            log(f"recover child failed rc={r.returncode}\n{r.stderr}")
            sys.exit(1)
        rec = json.loads(r.stdout.strip().splitlines()[-1])
        if not rec["replayed"]:
            log("recover child replayed nothing — the kill left no incomplete work?")
            sys.exit(1)

        ref = spawn("reference", ref_journal, args.seed)
        if ref.returncode != 0:
            log(f"reference child failed rc={ref.returncode}\n{ref.stderr}")
            sys.exit(1)
        expect = json.loads(ref.stdout.strip().splitlines()[-1])["outputs"]

        mismatches = [
            rid for rid in rec["replayed"]
            if rec["outputs"].get(str(rid)) != expect.get(str(rid))
        ]
        if mismatches:
            log(f"replay outputs DIVERGED for ids {mismatches}")
            sys.exit(1)

    record = {
        "metric": "serving_chaos_kill9_replay",
        "value": len(rec["replayed"]),
        "unit": "requests_replayed_bit_identical",
        "requests": N_REQUESTS,
        "kill_after_decodes": KILL_AFTER_DECODES,
        "victim_rc": v.returncode,
        "wall_s": round(time.monotonic() - t0, 1),
    }
    print(json.dumps(record), flush=True)
    log(
        f"OK: kill -9 mid-decode -> restart replayed {len(rec['replayed'])} "
        f"request(s) bit-identical to the uninterrupted run "
        f"({record['wall_s']}s)"
    )


if __name__ == "__main__":
    main()
