"""Kernel-suite microbench: lax reference vs Pallas (docs/kernels.md).

Drives the `kernels` bench rung (bench.py) and runs standalone:

    python tools/bench_kernels.py --dryrun     # CPU: tiny shapes, interpret kernels
    python tools/bench_kernels.py              # real devices: 2k/16k contexts
    python tools/bench_kernels.py --tune       # DS_KERNEL_AUTOTUNE=force block search

Measures, per (kv dtype, context) cell:

* ``flash_decode`` — single-query decode step over a slot pool, lax
  ``cache_attention`` vs the fused Pallas kernel (int8 cells keep the
  codes compressed to the register file); tokens/s = slots / step wall,
  plus the parity error vs the reference and the speedup ratio;
* ``fused_update`` — one optimizer step over a transformer-shaped
  param tree, stock XLA ``FusedAdam``/``FusedLamb`` vs the one-pass
  kernel; step wall plus the compiled-cost HBM bytes of each (the
  bytes column is the claim: same math, fewer passes).

Every record goes through ``tool_history_emit`` so ``bench_diff
--gate`` covers the kernels from the first run; the bench.py parent
appends for driver runs (DS_BENCH_CHILD=1 suppresses the local write).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

if "--dryrun" in sys.argv:
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def log(msg):
    print(f"[bench_kernels] {msg}", file=sys.stderr, flush=True)


def emit(rec):
    print(json.dumps(rec), flush=True)
    from deepspeed_tpu.telemetry.regression import tool_history_emit

    tool_history_emit(rec, rung="kernels",
                      base_dir=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time(fn, iters, *args):
    """Median-of-3 windows, fenced."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def bench_flash_decode(kv: str, S: int, B: int, H: int, d: int, iters: int, interpret: bool):
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.kernels.flash_decode import flash_decode
    from deepspeed_tpu.ops.transformer.inference import _kv_quant, cache_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, 1, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, H, S, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, d)), jnp.float32)
    if kv == "int8":
        kq, ks = _kv_quant(k)
        vq, vs = _kv_quant(v)
        kc, vc = {"q": kq, "s": ks}, {"q": vq, "s": vs}
    else:
        kc, vc = k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)
    pos = jnp.asarray(rng.integers(S // 2, S, B), jnp.int32)

    lax_fn = jax.jit(lambda q, kc, vc, p: cache_attention(q, kc, vc, p, use_kernel=False))
    kern_fn = jax.jit(lambda q, kc, vc, p: flash_decode(q, kc, vc, p, interpret=interpret))

    ref = lax_fn(q, kc, vc, pos)
    out = kern_fn(q, kc, vc, pos)
    err = float(jnp.max(jnp.abs(ref.astype(jnp.float32) - out.astype(jnp.float32))))

    t_lax = _time(lax_fn, iters, q, kc, vc, pos)
    t_kern = _time(kern_fn, iters, q, kc, vc, pos)
    label = f"{kv}_{S // 1024}k" if S >= 1024 else f"{kv}_{S}"
    return {
        "metric": f"flash_decode_{label}_tokens_per_sec",
        "value": round(B / t_kern, 1),
        "unit": "tokens/s",
        "slots": B, "heads": H, "head_dim": d, "context": S, "kv": kv,
        "lax_tokens_per_sec": round(B / t_lax, 1),
        "speedup_vs_lax": round(t_lax / t_kern, 3),
        "kernel_step_ms": round(t_kern * 1e3, 4),
        "lax_step_ms": round(t_lax * 1e3, 4),
        "max_abs_err_vs_lax": err,
    }


def _update_hbm_bytes(compiled) -> float:
    from deepspeed_tpu.profiling.flops_profiler import cost_bytes

    try:
        cost = compiled.cost_analysis() or {}
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        return float(cost_bytes({k: float(v) for k, v in cost.items() if np.isscalar(v)}))
    except Exception:  # noqa: BLE001 — bytes column is best-effort evidence
        return 0.0


def bench_fused_update(opt_kind: str, n_embd: int, n_layer: int, iters: int, interpret: bool):
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
    from deepspeed_tpu.ops.kernels import fused_update as fu
    from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb

    rng = np.random.default_rng(1)
    params = {}
    for i in range(n_layer):
        params[f"qkv_{i}"] = jnp.asarray(
            rng.standard_normal((n_embd, 3 * n_embd)) * 0.02, jnp.bfloat16)
        params[f"fc_{i}"] = jnp.asarray(
            rng.standard_normal((n_embd, 4 * n_embd)) * 0.02, jnp.bfloat16)
        params[f"ln_{i}"] = jnp.asarray(rng.standard_normal((n_embd,)), jnp.float32)
    n_params = sum(int(np.prod(np.shape(p))) for p in jax.tree.leaves(params))
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape) * 1e-3, p.dtype), params
    )
    opt = FusedLamb(lr=1e-3) if opt_kind == "lamb" else FusedAdam(lr=1e-3, weight_decay=0.01)
    state = opt.init(params)
    lr = jnp.float32(1e-3)
    overflow = jnp.bool_(False)

    def xla_step(g, st, p):
        upd, st2 = opt.update(g, st, p, lr=lr)
        p2 = jax.tree.map(
            lambda pp, u: (pp.astype(jnp.float32) + u).astype(pp.dtype), p, upd)
        return p2, st2

    def fused_step(g, st, p):
        res = fu.engine_update(opt, g, st, p, lr, overflow, interpret=interpret)
        assert res is not None
        return res

    xla_jit = jax.jit(xla_step)
    fused_jit = jax.jit(fused_step)
    t_xla = _time(xla_jit, iters, grads, state, params)
    t_fused = _time(fused_jit, iters, grads, state, params)
    b_xla = _update_hbm_bytes(xla_jit.lower(grads, state, params).compile())
    b_fused = _update_hbm_bytes(fused_jit.lower(grads, state, params).compile())
    p_x, _ = xla_jit(grads, state, params)
    p_f, _ = fused_jit(grads, state, params)
    err = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(jax.tree.leaves(p_x), jax.tree.leaves(p_f))
    )
    return {
        "metric": f"fused_update_{opt_kind}_ms",
        "value": round(t_fused * 1e3, 4),
        "unit": "ms",
        "n_params": n_params,
        "xla_ms": round(t_xla * 1e3, 4),
        "speedup_vs_xla": round(t_xla / t_fused, 3),
        "hbm_bytes_fused": b_fused,
        "hbm_bytes_xla": b_xla,
        "hbm_bytes_ratio": round(b_fused / b_xla, 3) if b_xla else None,
        "max_abs_err_vs_xla": err,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dryrun", action="store_true", help="CPU: tiny shapes, interpret kernels")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--tune", action="store_true",
                    help="run the measured block search first (needs DS_KERNEL_AUTOTUNE=force)")
    args = ap.parse_args()

    import jax

    from deepspeed_tpu.ops.kernels.compat import on_tpu_backend

    backend = jax.default_backend()
    on_tpu = on_tpu_backend()
    interpret = not on_tpu
    log(f"backend={backend} devices={jax.device_count()} dryrun={args.dryrun}")

    if args.dryrun:
        decode_cells = [("bf16", 256), ("int8", 256), ("bf16", 512), ("int8", 512)]
        B, H, d, iters = 4, 4, 64, 2
        upd_shape = (256, 2)  # n_embd, n_layer
        upd_iters = 2
    else:
        # 2k and 16k contexts per the issue; neo-2.7B-ish head geometry
        decode_cells = [("bf16", 2048), ("int8", 2048), ("bf16", 16384), ("int8", 16384)]
        B, H, d, iters = 8, 20, 128, 20
        upd_shape = (1280, 12)  # ~100M params of 774M-shaped leaves
        upd_iters = 10

    if args.tune and not args.dryrun:
        from deepspeed_tpu.ops.kernels.flash_decode import tune_decode_blocks

        for kv, S in decode_cells:
            blocks = tune_decode_blocks(B, H, S, d, kv_dtype="int8" if kv == "int8" else "bfloat16")
            log(f"tuned flash_decode {kv}@{S}: {blocks}")

    for kv, S in decode_cells:
        try:
            rec = bench_flash_decode(kv, S, B, H, d, args.iters or iters, interpret)
            if args.dryrun:
                rec["dryrun"] = True
            emit(rec)
            log(f"{rec['metric']}: {rec['value']} tok/s "
                f"(lax {rec['lax_tokens_per_sec']}, x{rec['speedup_vs_lax']}, "
                f"err {rec['max_abs_err_vs_lax']:.2e})")
        except Exception as e:  # noqa: BLE001 — one dead cell must not kill the sweep
            log(f"flash_decode {kv}@{S} FAILED: {str(e)[:200]}")
            emit({"metric": f"flash_decode_{kv}_{S}", "skipped": True, "reason": str(e)[:200]})

    for opt_kind in ("adam", "lamb"):
        try:
            rec = bench_fused_update(opt_kind, *upd_shape, args.iters or upd_iters, interpret)
            if args.dryrun:
                rec["dryrun"] = True
            emit(rec)
            log(f"{rec['metric']}: {rec['value']} ms (xla {rec['xla_ms']}, "
                f"bytes ratio {rec['hbm_bytes_ratio']})")
        except Exception as e:  # noqa: BLE001
            log(f"fused_update {opt_kind} FAILED: {str(e)[:200]}")
            emit({"metric": f"fused_update_{opt_kind}_ms", "skipped": True, "reason": str(e)[:200]})


if __name__ == "__main__":
    main()
