"""Host-I/O engine micro-bench: O_DIRECT kernel-AIO vs buffered
thread-pool (reference DeepNVMe benches, csrc/aio/py_test/).

Buffered wins while the blob fits page cache; kernel-AIO's number is the
device's sustained rate — the one ZeRO-Infinity sees once swap traffic
exceeds RAM (the reason the reference uses O_DIRECT).

Run: python tools/bench_aio.py [size_mb] [dir]
"""
import os
import sys
import tempfile
import time

import numpy as np

from deepspeed_tpu.ops.aio.aio import AioHandle


def main():
    size_mb = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    base = sys.argv[2] if len(sys.argv) > 2 else None
    d = tempfile.mkdtemp(dir=base)
    blob = np.frombuffer(np.random.default_rng(0).bytes(size_mb << 20), np.uint8).copy()
    print(f"{size_mb} MB blob in {d}")
    print(f"{'engine':>12s} {'write MB/s':>10s} {'read MB/s':>10s}")
    try:
        for name, env in (("kernel-aio", "0"), ("threadpool", "1")):
            os.environ["DS_AIO_DISABLE_KERNEL_AIO"] = env
            h = AioHandle(block_size=1 << 20, queue_depth=32, thread_count=8)
            path = os.path.join(d, f"bench_{name}.bin")
            t0 = time.perf_counter()
            h.sync_pwrite(blob, path)
            tw = time.perf_counter() - t0
            back = np.zeros_like(blob)
            t0 = time.perf_counter()
            h.sync_pread(back, path)
            tr = time.perf_counter() - t0
            assert (back == blob).all()
            tag = " (O_DIRECT)" if h.used_kernel_aio else ""
            print(f"{name:>12s} {blob.nbytes/tw/1e6:10.0f} {blob.nbytes/tr/1e6:10.0f}{tag}")
    finally:
        import shutil

        shutil.rmtree(d, ignore_errors=True)
        os.environ.pop("DS_AIO_DISABLE_KERNEL_AIO", None)


if __name__ == "__main__":
    main()
