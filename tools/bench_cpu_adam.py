"""Host (CPU) Adam kernel micro-bench: the C++ OpenMP kernel vs a numpy
baseline — the ZeRO-Offload step executor's throughput (reference
csrc/adam/cpu_adam.cpp AVX paths; VERDICT r1 flagged ours unmeasured).

Run: python tools/bench_cpu_adam.py [n_params_millions]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam


def numpy_adamw(p, g, m, v, step, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8, wd=0.01):
    np.multiply(m, b1, out=m)
    m += (1 - b1) * g
    np.multiply(v, b2, out=v)
    v += (1 - b2) * g * g
    upd = (m / (1 - b1**step)) / (np.sqrt(v / (1 - b2**step)) + eps) + wd * p
    p -= lr * upd


def main():
    n = int(float(sys.argv[1]) * 1e6) if len(sys.argv) > 1 else 100_000_000
    rng = np.random.default_rng(0)
    p = rng.standard_normal(n).astype(np.float32)
    g = rng.standard_normal(n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    opt = DeepSpeedCPUAdam(lr=1e-3, weight_decay=0.01, adamw_mode=True)
    print(f"{n/1e6:.0f}M fp32 params; native kernel: {opt.uses_native}")

    # native
    opt.step(p, g, m, v, 1)  # warm
    best = float("inf")
    for s in range(2, 5):
        t0 = time.perf_counter()
        opt.step(p, g, m, v, s)
        best = min(best, time.perf_counter() - t0)
    # params/s and effective GB/s (reads p,g,m,v + writes p,m,v = 7 arrays)
    print(f"native : {best*1e3:7.1f} ms/step  {n/best/1e9:5.2f} Gparam/s  {7*4*n/best/1e9:5.1f} GB/s")

    p2 = rng.standard_normal(n).astype(np.float32)
    m2 = np.zeros(n, np.float32)
    v2 = np.zeros(n, np.float32)
    numpy_adamw(p2, g, m2, v2, 1)
    best_np = float("inf")
    for s in range(2, 4):
        t0 = time.perf_counter()
        numpy_adamw(p2, g, m2, v2, s)
        best_np = min(best_np, time.perf_counter() - t0)
    print(f"numpy  : {best_np*1e3:7.1f} ms/step  {n/best_np/1e9:5.2f} Gparam/s  ({best_np/best:.1f}x slower)")


if __name__ == "__main__":
    main()
