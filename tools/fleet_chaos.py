"""Fleet chaos smoke: 3 REAL replica processes, one SIGKILL'd
mid-decode under load, supervised restart + journal replay — asserted
zero acknowledged loss and bit-identical outputs (the ``fleet-chaos``
CI job; docs/serving.md §Fleet).

The in-process form of this proof lives in tests/test_fleet.py (the
engine object is dropped without drain).  This tool runs the real
thing: each replica is a CHILD PROCESS serving a JSONL command pipe —
the replica surface the :class:`~deepspeed_tpu.serving.fleet.router.
FleetRouter` routes against, duck-typed over stdin/stdout — and the
victim carries a seeded ``DS_FAULT_PLAN`` that ``SIGKILL``\\ s it at its
Nth decode dispatch.  No Python unwinding, no atexit: the pipe EOF the
parent observes is exactly what the PR 5 heartbeat channel sees when a
rank dies.

    python tools/fleet_chaos.py --dryrun        # tiny model, CPU

Flow: the parent builds the router over three :class:`ProcessReplica`
handles + a :class:`~deepspeed_tpu.serving.fleet.supervisor.
ReplicaSupervisor` whose ``restart()`` respawns the child over the SAME
journal directory (without the fault plan) and replays.  A seeded
workload routes through ``router.submit``; the victim dies mid-stream;
the router fails over, the supervisor restarts, the journal replays
under original ids — and the parent asserts:

* the victim's first incarnation died to SIGKILL (rc == -9);
* ZERO acknowledged loss — every routed handle resolves;
* every output is bit-identical to an uninterrupted solo
  ``generate()`` of the same prompt (deterministic serving contract).

``--elastic`` runs the elastic-fleet proof instead (docs/serving.md
§Elastic fleet): one paged replica + a :class:`FleetAutoscaler` whose
warm pool pre-compiles child processes off the routing thread.  A
burst drives queue depth over the scale-up threshold (reaction time
recorded); multi-turn KV sessions are parked on the elastic replicas;
a forced scale-down of a victim armed with ``DS_FAULT_PLAN`` SIGKILLs
it INSIDE ``migrate.export`` (rc == -9, journal-replay fallback); a
second, clean scale-down live-migrates the surviving sessions to the
last replica over the spill wire format — and the final session turns,
served by a replica that never saw turns 1..2, must rebind the
migrated KV and bit-match the uninterrupted solo transcript.

    python tools/fleet_chaos.py --dryrun --elastic
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time

if "--dryrun" in sys.argv or os.environ.get("JAX_PLATFORMS") is None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_REPLICAS = 3
N_REQUESTS = 9
MAX_NEW = 6
KILL_AFTER_DECODES = 5

# --elastic mode
E_SESSIONS = 3
E_TURNS = 3
E_BURST = 8
E_PAGE_LEN = 8


def log(msg):
    print(f"[fleet_chaos] {msg}", file=sys.stderr, flush=True)


def build_prompts(seed, vocab):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, vocab, int(rng.integers(4, 20)), dtype=np.int32)
        for _ in range(N_REQUESTS)
    ]


def make_engine(journal_dir, paged=False, spill_dir=None):
    import dataclasses

    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.serving import ServingEngine

    cfg = dataclasses.replace(gpt2.GPT2_TINY, remat=False)
    params = gpt2.init_params(cfg, seed=7)
    params["wpe"] = params["wpe"] * 40.0
    eng = deepspeed_tpu.init_inference(
        model_config=cfg, params=params, dtype=jnp.float32,
        max_out_tokens=cfg.n_positions,
    )
    kw = {}
    if paged:
        kw["kvcache"] = {
            "enabled": True,
            "page_len": E_PAGE_LEN,
            "spill_dir": spill_dir or "",
        }
    srv = ServingEngine(
        eng, num_slots=2, prefill_chunk=8, max_len=64, journal_dir=journal_dir,
        **kw,
    )
    return cfg, eng, srv


# ---------------------------------------------------------------------------
# worker child: a replica process serving the framed RPC stream
# ---------------------------------------------------------------------------

def run_worker(journal_dir, paged=False, spill_dir=None):
    """One replica process: engine over ``journal_dir`` wrapped in a
    :class:`LocalReplica`, served over the crc-framed RPC codec
    (serving/frontdoor/transport.py) on the stdio pipes.  A planned
    SIGKILL (DS_FAULT_PLAN, site ``serving.decode`` or
    ``migrate.export``) simply never answers — the parent's read hits
    EOF, which IS the death signal."""
    # claim fd 0/1 as the private framed channel BEFORE the framework
    # loads: the deepspeed_tpu logger writes to stdout, which would
    # corrupt the framing — re-point fd 1 (and sys.stdout) at stderr
    rfile = os.fdopen(os.dup(0), "rb")
    wfile = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = sys.stderr

    from deepspeed_tpu.resilience import faults

    faults.install_from_env(rank=0)

    from deepspeed_tpu.serving.fleet import LocalReplica
    from deepspeed_tpu.serving.frontdoor.transport import serve_stream

    rep = LocalReplica(
        "worker",
        lambda: make_engine(journal_dir, paged=paged, spill_dir=spill_dir)[2],
    )
    serve_stream(rep, rfile, wfile)


# ---------------------------------------------------------------------------
# parent-side process replica: the router's duck-typed surface, now the
# shared TransportReplica over a ProcessTransport (one codec both ways)
# ---------------------------------------------------------------------------

def ProcessReplica(name, journal_dir, fault_plan=None, paged=False,
                   spill_dir=None):
    """The fleet replica surface over a child worker process: a
    :class:`TransportReplica` driving a :class:`ProcessTransport`.
    Pipe EOF or a torn frame raises :class:`ReplicaDeadError` — the
    parent-side shape of a SIGKILL'd replica.  ``restart()`` respawns
    the child over the same journal directory (sans fault plan) and
    replays.  (A factory, not a class: the transport import must stay
    out of module scope so a worker child can claim fd 1 before the
    framework's first stdout write.)"""
    from deepspeed_tpu.serving.frontdoor.transport import (
        ProcessTransport,
        TransportReplica,
    )

    argv = [sys.executable, os.path.abspath(__file__), "--role", "worker",
            "--journal", journal_dir, "--dryrun"]
    if paged:
        argv.append("--paged")
    if spill_dir:
        argv += ["--spill", spill_dir]
    rep = TransportReplica(name, ProcessTransport(name, argv,
                                                  fault_plan=fault_plan))
    rep.journal_dir = journal_dir
    rep.paged = paged
    rep.spill_dir = spill_dir
    return rep


# ---------------------------------------------------------------------------
# parent: route, kill, recover, assert
# ---------------------------------------------------------------------------

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dryrun", action="store_true", help="tiny model on CPU")
    ap.add_argument("--role", default=None, choices=(None, "worker"))
    ap.add_argument("--journal", default=None)
    ap.add_argument("--paged", action="store_true",
                    help="worker: paged KV pool (sessions + migration)")
    ap.add_argument("--spill", default=None,
                    help="worker: session spill directory")
    ap.add_argument("--elastic", action="store_true",
                    help="run the elastic-fleet proof (autoscale + live "
                    "KV migration + kill -9 mid-export)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.role == "worker":
        run_worker(args.journal, paged=args.paged, spill_dir=args.spill)
        return
    if args.elastic:
        run_elastic(args)
        return

    import numpy as np

    from deepspeed_tpu.resilience.faults import plan_json
    from deepspeed_tpu.serving.fleet import FleetRouter, ReplicaSupervisor

    t0 = time.monotonic()
    rng = np.random.default_rng(args.seed)
    with tempfile.TemporaryDirectory(prefix="fleet_chaos_") as root:
        # the reference: uninterrupted solo generate() in the parent —
        # the deterministic-serving contract says every fleet output
        # must bit-match it regardless of batching, failover, or replay
        cfg, eng, _ = make_engine(os.path.join(root, "ref-journal"))
        prompts = build_prompts(args.seed, cfg.vocab_size)
        expect = [
            [int(t) for t in
             np.asarray(eng.generate(p[None, :], max_new_tokens=MAX_NEW))[0]]
            for p in prompts
        ]

        plan = plan_json([
            {"site": "serving.decode", "action": "sigkill",
             "after": KILL_AFTER_DECODES},
        ])
        reps = [
            ProcessReplica(
                f"r{i}", os.path.join(root, f"r{i}", "journal"),
                fault_plan=plan if i == 0 else None,
            )
            for i in range(N_REPLICAS)
        ]
        log(f"{N_REPLICAS} replica processes up; r0 armed to SIGKILL at "
            f"decode dispatch {KILL_AFTER_DECODES + 1}")
        router = FleetRouter(
            reps, supervisor=ReplicaSupervisor(max_restarts=2),
        )
        try:
            hids = []
            for i, p in enumerate(prompts):
                hids.append(router.submit(p, max_new_tokens=MAX_NEW,
                                          client_key=f"chaos-{i}"))
                for _ in range(int(rng.poisson(1.0))):
                    router.step()
            res = router.drain(max_steps=3000)
        finally:
            for rep in reps:
                rep.close()

        victim = reps[0]
        if victim.first_rc != -signal.SIGKILL:
            log(f"victim first incarnation rc={victim.first_rc}, expected "
                f"{-signal.SIGKILL} — the fault plan did not fire")
            sys.exit(1)
        log(f"victim r0 died to SIGKILL mid-decode (rc={victim.first_rc}) "
            f"and was restarted {victim.kills} time(s)")

        missing = sorted(set(hids) - set(res))
        if missing:
            log(f"ACKNOWLEDGED LOSS: handles {missing} never resolved")
            sys.exit(1)
        mismatches = [
            i for i, hid in enumerate(hids)
            if list(res[hid].tokens()) != expect[i]
        ]
        if mismatches:
            log(f"outputs DIVERGED from solo generate() for requests "
                f"{mismatches}")
            sys.exit(1)
        st = router.stats()
        if st["deaths"] < 1 or st["restarts"] < 1:
            log(f"router saw no death/restart cycle: {st}")
            sys.exit(1)

    record = {
        "metric": "fleet_chaos_kill9_zero_loss",
        "value": len(hids),
        "unit": "requests_resolved_bit_identical",
        "replicas": N_REPLICAS,
        "kill_after_decodes": KILL_AFTER_DECODES,
        "victim_rc": victim.first_rc,
        "deaths": st["deaths"],
        "restarts": st["restarts"],
        "failovers": st["failovers"],
        "refired": st["refired"],
        "wall_s": round(time.monotonic() - t0, 1),
    }
    print(json.dumps(record), flush=True)
    log(
        f"OK: SIGKILL'd 1/{N_REPLICAS} replicas mid-decode -> zero "
        f"acknowledged loss, {len(hids)}/{len(hids)} outputs bit-identical "
        f"({record['wall_s']}s)"
    )


# ---------------------------------------------------------------------------
# --elastic: autoscale + live KV migration + kill -9 mid-export
# ---------------------------------------------------------------------------

def build_session_scripts(seed, eng, vocab):
    """``E_SESSIONS`` sessions x ``E_TURNS`` turns: turn t's prompt is
    turn t-1's full output plus fresh tokens, and the expected output of
    every turn is an uninterrupted solo ``generate()`` over the full
    context — the deterministic-serving bar the fleet must meet across
    park, migrate, and rebind."""
    import numpy as np

    rng = np.random.default_rng(seed + 1)
    prompts, expect = [], []
    for _ in range(E_SESSIONS):
        p, e = [], []
        ctx = rng.integers(1, vocab, int(rng.integers(6, 12)), dtype=np.int32)
        for turn in range(E_TURNS):
            if turn:
                ext = rng.integers(1, vocab, int(rng.integers(4, 7)),
                                   dtype=np.int32)
                ctx = np.concatenate([np.asarray(e[-1], np.int32), ext])
            p.append(ctx.copy())
            e.append([int(t) for t in np.asarray(
                eng.generate(ctx[None, :], max_new_tokens=MAX_NEW))[0]])
        prompts.append(p)
        expect.append(e)
    return prompts, expect


def run_elastic(args):
    import numpy as np

    from deepspeed_tpu.resilience.faults import plan_json
    from deepspeed_tpu.serving.fleet import (
        HEALTHY,
        FleetAutoscaler,
        FleetOverloaded,
        FleetRouter,
        ReplicaSupervisor,
    )

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="fleet_elastic_") as root:
        cfg, eng, _ = make_engine(os.path.join(root, "ref-journal"))
        burst = build_prompts(args.seed, cfg.vocab_size)[:E_BURST]
        burst_expect = [
            [int(t) for t in
             np.asarray(eng.generate(p[None, :], max_new_tokens=MAX_NEW))[0]]
            for p in burst
        ]
        sess_prompts, sess_expect = build_session_scripts(
            args.seed, eng, cfg.vocab_size
        )

        def spawn(name, fault_plan=None):
            return ProcessReplica(
                name, os.path.join(root, name, "journal"),
                fault_plan=fault_plan, paged=True,
                spill_dir=os.path.join(root, name, "spill"),
            )

        def factory(name):
            rep = spawn(name)
            rep.queue_depth()  # block HERE (warm-pool thread) on compile
            return rep

        r0 = factory("r0")
        router = FleetRouter([r0], supervisor=ReplicaSupervisor(max_restarts=2))
        auto = FleetAutoscaler(
            router, factory,
            config={
                "enabled": True, "min_replicas": 1, "max_replicas": 3,
                "scale_up_queue_depth": 2, "scale_up_ttft_seconds": 30.0,
                "scale_down_queue_depth": 1, "engage_ticks": 2,
                # scale-down is FORCED in this proof, never load-driven
                "disengage_ticks": 10 ** 6,
                "scale_up_cooldown_seconds": 0.0,
                "scale_down_cooldown_seconds": 0.0,
                "warm_pool_size": 1,
                "migration_deadline_seconds": 120.0,
                "migration_retries": 2,
            },
            handoff_root=root,
        )
        hids, res = {}, {}
        try:
            # phase 1 — burst over one replica's comfort: the autoscaler
            # must adopt a PRE-COMPILED replica off the warm pool
            deadline = time.monotonic() + 300
            while auto.pool.ready() < 1 and time.monotonic() < deadline:
                time.sleep(0.2)
            if auto.pool.ready() < 1:
                log("warm pool never produced a replica")
                sys.exit(1)
            for i, p in enumerate(burst):
                while True:
                    try:
                        hids[("burst", i)] = router.submit(
                            p, max_new_tokens=MAX_NEW, client_key=f"b{i}")
                        break
                    except FleetOverloaded as e:
                        time.sleep(min(e.retry_after or 0.05, 0.2))
                        router.step()
                auto.tick()
                if i % 3 == 2:
                    router.step()
            st = auto.stats()
            if st["scale_ups"] < 1:
                log(f"burst never triggered a scale-up: {st}")
                sys.exit(1)
            log(f"scaled UP to {st['replicas']} replicas in "
                f"{st['last_scale_up_reaction_s']:.3f}s reaction")
            res.update(router.drain(max_steps=8000))

            # phase 2 — park sessions on the ELASTIC replicas only:
            # r0 drains through turns 1..2 so every parked session lives
            # on a replica that is about to be scaled away
            plan = plan_json([{"site": "migrate.export", "action": "sigkill"}])
            v0 = spawn("v0", fault_plan=plan)
            router.add_replica(v0)
            router.begin_drain("r0", "pin sessions to elastic replicas")
            for turn in range(E_TURNS - 1):
                for s in range(E_SESSIONS):
                    hids[("sess", s, turn)] = router.submit(
                        sess_prompts[s][turn], max_new_tokens=MAX_NEW,
                        client_key=f"s{s}t{turn}", session_id=f"sess-{s}")
                res.update(router.drain(max_steps=8000))
            router.abort_drain("r0")

            # phase 3 — forced scale-down of v0, which SIGKILLs itself
            # inside migrate.export: the autoscaler must fall back to the
            # death path (supervisor restart + journal replay), not hang
            # and not lose acknowledged work
            if not auto.request_scale_down("v0"):
                log("scale-down of v0 refused")
                sys.exit(1)
            deadline = time.monotonic() + 300
            while auto.stats()["phase"] != "idle":
                auto.tick()
                router.step()
                if time.monotonic() > deadline:
                    log(f"drain of v0 never settled: {auto.stats()}")
                    sys.exit(1)
            if v0.first_rc != -signal.SIGKILL:
                log(f"victim v0 rc={v0.first_rc}, expected {-signal.SIGKILL} "
                    "— the migrate.export fault plan did not fire")
                sys.exit(1)
            if auto.migrations_failed < 1:
                log(f"no failed migration recorded: {auto.stats()}")
                sys.exit(1)
            deadline = time.monotonic() + 300
            while not (v0.alive()
                       and router._health["v0"].state == HEALTHY):
                router.step()
                if time.monotonic() > deadline:
                    log("v0 was never restarted after dying mid-export")
                    sys.exit(1)
                time.sleep(0.05)
            res.update(router.drain(max_steps=8000))  # replayed work
            log(f"v0 died to SIGKILL inside migrate.export "
                f"(rc={v0.first_rc}); journal replay restored it")

            # phase 4 — CLEAN scale-downs back to min_replicas: each
            # victim's parked sessions live-migrate to a survivor over
            # the spill wire format (v0 -> e*, then e* -> r0)
            while len(router._order) > 1:
                victim = [n for n in router._order if n != "r0"][-1]
                if not auto.request_scale_down(victim):
                    log(f"scale-down of {victim} refused: {auto.stats()}")
                    sys.exit(1)
                deadline = time.monotonic() + 300
                while auto.stats()["phase"] != "idle":
                    auto.tick()
                    router.step()
                    if time.monotonic() > deadline:
                        log(f"scale-down of {victim} never settled: "
                            f"{auto.stats()}")
                        sys.exit(1)
            st = auto.stats()
            if st["migrations_completed"] < 1 or st["sessions_migrated"] < 1:
                log(f"no live migration happened: {st}")
                sys.exit(1)
            log(f"scaled DOWN to {st['replicas']} replica(s); "
                f"{st['sessions_migrated']} session(s) live-migrated")

            # phase 5 — final turn on the ONE survivor, which never
            # served turns 1..2: only the migrated KV can rebind
            for s in range(E_SESSIONS):
                hids[("sess", s, E_TURNS - 1)] = router.submit(
                    sess_prompts[s][E_TURNS - 1], max_new_tokens=MAX_NEW,
                    client_key=f"s{s}t{E_TURNS - 1}", session_id=f"sess-{s}")
            res.update(router.drain(max_steps=8000))
            kv = r0.kv_stats()
            rebinds = int(kv.get("session_rebinds", 0)) + int(
                kv.get("session_restores", 0))
        finally:
            auto.stop()
            for rep in list(router._replicas.values()):
                try:
                    rep.close()
                except Exception:
                    pass
            for rep in list(auto.pool._ready):  # built but never adopted
                try:
                    rep.close()
                except Exception:
                    pass

        missing = sorted(k for k, hid in hids.items() if hid not in res)
        if missing:
            log(f"ACKNOWLEDGED LOSS: requests {missing} never resolved")
            sys.exit(1)
        mismatches = []
        for i in range(len(burst)):
            if list(res[hids[("burst", i)]].tokens()) != burst_expect[i]:
                mismatches.append(("burst", i))
        for s in range(E_SESSIONS):
            for turn in range(E_TURNS):
                if (list(res[hids[("sess", s, turn)]].tokens())
                        != sess_expect[s][turn]):
                    mismatches.append(("sess", s, turn))
        if mismatches:
            log(f"outputs DIVERGED from solo generate() for {mismatches}")
            sys.exit(1)
        if rebinds < 1:
            log("the survivor never rebound a migrated session — the "
                f"migration was dead weight: {kv}")
            sys.exit(1)

    record = {
        "metric": "fleet_elastic_migration_zero_loss",
        "value": len(hids),
        "unit": "requests_resolved_bit_identical",
        "sessions": E_SESSIONS,
        "turns": E_TURNS,
        "victim_rc": v0.first_rc,
        "scale_ups": st["scale_ups"],
        "scale_downs": st["scale_downs"],
        "migrations_completed": st["migrations_completed"],
        "migrations_failed": st["migrations_failed"],
        "sessions_migrated": st["sessions_migrated"],
        "scale_up_reaction_s": round(st["last_scale_up_reaction_s"] or 0, 3),
        "scale_down_reaction_s": round(
            st["last_scale_down_reaction_s"] or 0, 3),
        "survivor_rebinds": rebinds,
        "wall_s": round(time.monotonic() - t0, 1),
    }
    print(json.dumps(record), flush=True)
    log(
        f"OK: scale-up {st['scale_ups']}x, kill -9 mid-export survived, "
        f"{st['sessions_migrated']} session(s) live-migrated, "
        f"{len(hids)}/{len(hids)} outputs bit-identical "
        f"({record['wall_s']}s)"
    )


if __name__ == "__main__":
    main()
