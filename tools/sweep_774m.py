"""774M ZeRO-3 MFU sweep — one config per process (clean HBM each run).

Usage: python tools/sweep_774m.py <name>
Names map to (remat policy, micro_bs, gas, scan_unroll) combos; prints a
single summary line on stdout.  Driven by the round-3 MFU work
(VERDICT r2 #1: lift 774M decisively clear of the 35% north star).
"""
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SAVE_ALL = ("qkv", "attn_ctx", "ffn_pre")
SAVE_SMALL = ("qkv", "attn_ctx")
# + kernel residuals: backward never re-runs the flash fwd (lse saved)
SAVE_FLASH = ("qkv", "ffn_pre", "attn_o", "attn_lse")

# name -> dict(cfg overrides, micro_bs, gas)
CONFIGS = {
    # round-2 record configuration (the 35.4% reference point)
    "r2": dict(model=dict(remat=True, xent_chunk_size=512, remat_policy="nothing_saveable"), mb=4, gas=2),
    # selective remat + fused gas==1 (no persistent fp32 accumulator)
    "sel1": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_ALL), mb=4, gas=1),
    "sel1u6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_ALL, scan_unroll=6), mb=4, gas=1),
    "sel1u12": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_ALL, scan_unroll=12), mb=4, gas=1),
    "sel1u36": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_ALL, scan_unroll=36), mb=4, gas=1),
    # smaller saved set → fits gas=2 (update cost amortized over 2 micros)
    "sel2": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_SMALL), mb=4, gas=2),
    "sel2g2u6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_SMALL, scan_unroll=6), mb=4, gas=2),
    # dots policy for comparison
    "dots1": dict(model=dict(remat=True, xent_chunk_size=512, remat_policy="dots_with_no_batch_dims_saveable"), mb=4, gas=1),
    # nothing_saveable + gas1 (isolates the accumulator-free effect)
    "ns1": dict(model=dict(remat=True, xent_chunk_size=512, remat_policy="nothing_saveable"), mb=4, gas=1),
    # grouped unroll on the r2 config (isolates unroll effect under full recompute)
    "r2u6": dict(model=dict(remat=True, xent_chunk_size=512, remat_policy="nothing_saveable", scan_unroll=6), mb=4, gas=2),
    # round 2 of the sweep: memory headroom for the unroll
    "sel2u6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=("qkv", "ffn_pre"), scan_unroll=6), mb=4, gas=1),
    "sel2u12": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=("qkv", "ffn_pre"), scan_unroll=12), mb=4, gas=1),
    "sel3u6": dict(model=dict(remat=True, xent_chunk_size=256, remat_save_names=SAVE_ALL, scan_unroll=6), mb=4, gas=1),
    "mb6u6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=("qkv", "ffn_pre"), scan_unroll=6), mb=6, gas=1),
    "ns1u6": dict(model=dict(remat=True, xent_chunk_size=512, remat_policy="nothing_saveable", scan_unroll=6), mb=4, gas=1),
    # round 3: save flash residuals (no kernel re-run in bwd) + tuned blocks
    "self": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=4, gas=1),
    "selfa": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH + ("attn_ctx",)), mb=4, gas=1),
    # round 4: amortize the fixed ~43ms optimizer/elementwise cost over
    # more tokens per step (saved-activation memory scales with mb; the
    # gas==1 fused step freed the 3.1GB accumulator that pays for it)
    "mb6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=6, gas=1),
    "mb8": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=8, gas=1),
    "x1024": dict(model=dict(remat=True, xent_chunk_size=1024, remat_save_names=SAVE_FLASH), mb=4, gas=1),
    "mb6x1024": dict(model=dict(remat=True, xent_chunk_size=1024, remat_save_names=SAVE_FLASH), mb=6, gas=1),
    "mb8small": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=("qkv", "attn_o", "attn_lse")), mb=8, gas=1),
    "mb6small": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=("qkv", "attn_o", "attn_lse")), mb=6, gas=1),
    # round 4 cont.: scan unroll on the SAVE_FLASH set with the fused
    # single-pass attention backward (the DUS scan bookkeeping was
    # ~30ms of the r4 profile's top ops)
    "selfu6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH, scan_unroll=6), mb=4, gas=1),
    "selfu12": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH, scan_unroll=12), mb=4, gas=1),
    # split fwd/bwd flash blocks (fwd prefers (1024,256), fused bwd (512,512))
    "fb_split": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH, flash_blocks=(1024, 256, 512, 512)), mb=4, gas=1),
    "fb_1024_512": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH, flash_blocks=(1024, 512, 512, 512)), mb=4, gas=1),
    # gas=2 with the fused bwd: the ~27ms fp32 Adam HBM pass amortizes
    # over 2 micros (r3's gas2 lost to the fp32 accumulator's memory
    # pressure under nothing_saveable; SAVE_FLASH changes the balance)
    "selfg2": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=4, gas=2),
    "selfg4": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=4, gas=4),
    "x256": dict(model=dict(remat=True, xent_chunk_size=256, remat_save_names=SAVE_FLASH), mb=4, gas=1),
    "x768": dict(model=dict(remat=True, xent_chunk_size=768, remat_save_names=SAVE_FLASH), mb=4, gas=1),
    "x2048": dict(model=dict(remat=True, xent_chunk_size=2048, remat_save_names=SAVE_FLASH), mb=4, gas=1),
    # round 5: 8-bit Adam state (m bf16, v uint8 sqrt-codes) — the fp32
    # m/v HBM pass was the r4-attributed ~27ms dominant loss; 8-bit cuts
    # state traffic 16 B/param -> ~5 (r+w) and frees ~3.9 GB of HBM,
    # which may also re-open mb=6/gas=2 (OOM at fp32 state in r4)
    "q8": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=4, gas=1, opt=dict(state_precision="8bit")),
    "q8g2": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=4, gas=2, opt=dict(state_precision="8bit")),
    "q8mb6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=6, gas=1, opt=dict(state_precision="8bit")),
    "q8mb8": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=8, gas=1, opt=dict(state_precision="8bit")),
    "q8u6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH, scan_unroll=6), mb=4, gas=1, opt=dict(state_precision="8bit")),
    # bf16 state (native dtype, SR on the v store): no uint8 relayout
    "qb16": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=4, gas=1, opt=dict(state_precision="bf16")),
    "qb16g2": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=4, gas=2, opt=dict(state_precision="bf16")),
    "qb16mb6": dict(model=dict(remat=True, xent_chunk_size=512, remat_save_names=SAVE_FLASH), mb=6, gas=1, opt=dict(state_precision="bf16")),
}


def main():
    name = sys.argv[1]
    c = CONFIGS[name]
    import bench
    from deepspeed_tpu.models import gpt2

    cfg = dataclasses.replace(gpt2.GPT2_LARGE, **c["model"])
    out = bench.bench_model(
        cfg, micro_bs=c["mb"], gas=c["gas"], seq=1024, steps=4, zero_stage=3,
        label=f"774M-{name}", opt_params=c.get("opt"),
    )
    print(json.dumps({"name": name, **out}), flush=True)


if __name__ == "__main__":
    main()
