"""Profile one compiled BERT-Large train step on the real chip (same
per-source / per-HLO-category attribution as profile_train_step.py, for
the seq128 samples/s rung — VERDICT r2 #8).

Run: python tools/profile_bert_step.py [seq] [micro_bs]
"""
import collections
import dataclasses
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import bert
    from deepspeed_tpu.runtime.engine import _PlacedBatch

    seq = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    mb = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    steps = 3

    cfg = dataclasses.replace(
        bert.BERT_LARGE, remat=False, scan_unroll=bert.BERT_LARGE.num_hidden_layers
    )
    model_fn, init_fn, tp_fn = bert.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": mb,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config, tp_spec_fn=tp_fn
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (mb, seq), dtype=np.int32)
    placed = _PlacedBatch(
        engine._stack_and_place(
            {
                "input_ids": ids,
                "masked_lm_labels": np.where(
                    rng.random((mb, seq)) < 0.15, ids, -100
                ).astype(np.int32),
                "next_sentence_label": rng.integers(0, 2, (mb,), dtype=np.int32),
            }
        )
    )
    loss = engine.train_batch(placed)
    float(loss)

    trace_dir = tempfile.mkdtemp(prefix="tpu_trace_")
    with jax.profiler.trace(trace_dir):
        for _ in range(steps):
            loss = engine.train_batch(placed)
        float(loss)

    f = sorted(glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")))[-1]
    with gzip.open(f) as fh:
        data = json.load(fh)
    ev = [
        e
        for e in data["traceEvents"]
        if e.get("ph") == "X" and e.get("args") and e["args"].get("hlo_category")
    ]
    src_t = collections.Counter()
    src_f = collections.Counter()
    for e in ev:
        if e["args"]["hlo_category"] in ("while", "conditional", "call"):
            continue
        s = e["args"].get("source", "?")
        src_t[s] += e["dur"]
        src_f[s] += int(e["args"].get("model_flops", 0) or 0)
    print(f"{'source':68s} {'ms/step':>8s} {'TFLOP/s':>8s}")
    for s, t in src_t.most_common(20):
        tf = src_f[s] / (t * 1e-6) / 1e12 if t else 0
        print(f"{s[-68:]:68s} {t/1e3/steps:8.1f} {tf:8.1f}")

    cat_t = collections.Counter()
    cat_f = collections.Counter()
    op_t = collections.Counter()
    for e in ev:
        c = e["args"]["hlo_category"]
        if c in ("while", "conditional", "call"):
            continue
        cat_t[c] += e["dur"]
        cat_f[c] += int(e["args"].get("model_flops", 0) or 0)
        op_t[e.get("name", "?")[:70]] += e["dur"]
    print(f"\n{'hlo category':30s} {'ms/step':>8s} {'TFLOP/s':>8s}")
    for c, t in cat_t.most_common(12):
        tf = cat_f[c] / (t * 1e-6) / 1e12 if t else 0
        print(f"{c:30s} {t/1e3/steps:8.1f} {tf:8.1f}")
    print(f"\n{'top ops':70s} {'ms/step':>8s}")
    for o, t in op_t.most_common(15):
        print(f"{o:70s} {t/1e3/steps:8.1f}")


if __name__ == "__main__":
    main()
