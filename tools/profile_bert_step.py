"""Profile one compiled BERT-Large train step on the real chip (the
seq128 samples/s rung — VERDICT r2 #8).  Same per-source /
per-HLO-category cost walk as profile_train_step.py, now shared via
``deepspeed_tpu.telemetry.attribution`` — this script is only the BERT
harness.

Run: python tools/profile_bert_step.py [seq] [micro_bs]
"""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import bert
    from deepspeed_tpu.runtime.engine import _PlacedBatch
    from deepspeed_tpu.telemetry.attribution import (
        format_trace_tables,
        profile_and_report,
    )

    seq = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    mb = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    steps = 3

    cfg = dataclasses.replace(
        bert.BERT_LARGE, remat=False, scan_unroll=bert.BERT_LARGE.num_hidden_layers
    )
    model_fn, init_fn, tp_fn = bert.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": mb,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config, tp_spec_fn=tp_fn
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (mb, seq), dtype=np.int32)
    placed = _PlacedBatch(
        engine._stack_and_place(
            {
                "input_ids": ids,
                "masked_lm_labels": np.where(
                    rng.random((mb, seq)) < 0.15, ids, -100
                ).astype(np.int32),
                "next_sentence_label": rng.integers(0, 2, (mb,), dtype=np.int32),
            }
        )
    )
    loss = engine.train_batch(placed)
    float(loss)

    def one_step():
        nonlocal loss
        loss = engine.train_batch(placed)

    tables = profile_and_report(one_step, steps=steps, sync=lambda: float(loss))
    print(format_trace_tables(tables, unit="step"))

    attr = engine.train_step_attribution()
    if attr is not None:
        print()
        print(attr.format_table())


if __name__ == "__main__":
    main()
