"""BERT-Large seq128 micro-batch sweep (VERDICT r2 #8: close or explain
the 258.7 vs 272 samples/s gap on the reference's seq128 rung).

Run: python tools/bench_bert_sweep.py [seq]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import bench

    seq = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    for mb in (16, 32, 48, 64, 96):
        try:
            r = bench.bench_bert(seq=seq, micro_bs=mb, gas=1, steps=6)
            print({"micro_bs": mb, **r}, flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"mb={mb} FAILED: {str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()
