"""Front-door chaos smoke: a REAL HTTP server process streaming token
chunks, SIGKILL'd mid-stream, restarted over the same journal — the
client's re-POST with the same ``client_key`` must resume the SAME
request id (at-most-once admission over the HTTP boundary) and the
full replayed stream must extend the pre-crash prefix bit-identically
to an uninterrupted solo ``generate()``.  Then the SIGTERM leg: a
drain signal mid-stream must finish streaming the in-flight request,
answer new submits 503 + ``Retry-After``, and exit 43 only after the
journal commit (the ``frontdoor`` CI job; docs/serving.md
§Front-door).

    python tools/frontdoor_chaos.py --dryrun

Phases:

1. warmup + throttle probe — a blocking request compiles the engine;
   a starved tenant's POST must answer 429 with a ``Retry-After``
   header and ``"type": "TenantThrottled"`` in the body.
2. kill -9 mid-stream — the server carries a seeded ``DS_FAULT_PLAN``
   (``frontdoor.stream`` sigkill): the chunked response dies without
   its terminating chunk (rc == -9), the parent keeps the observed
   token prefix.
3. recover + resume — a fresh server over the SAME journal replays;
   re-POSTing the same ``client_key`` returns the ORIGINAL request id
   and streams the full output; asserted prefix-consistent and
   bit-identical to solo ``generate()``.
4. SIGTERM drain — a new stream is cut by SIGTERM after its first
   chunk: the stream must still complete (terminating chunk arrives),
   a probe POST during the drain answers 503 + ``Retry-After``, and
   the server exits 43.
5. accounting — ``journal_tenant_totals`` over the shared journal must
   show exactly one admission per client key and per-tenant billed
   tokens equal to the client-observed stream lengths (no double-bill
   across the crash, no loss).
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

if "--dryrun" in sys.argv or os.environ.get("JAX_PLATFORMS") is None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MAX_NEW = 24
DRAIN_MAX_NEW = 48
KILL_AFTER_CHUNKS = 2


def log(msg):
    print(f"[frontdoor_chaos] {msg}", file=sys.stderr, flush=True)


def make_engine(journal_dir):
    import dataclasses

    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.serving import ServingEngine

    cfg = dataclasses.replace(gpt2.GPT2_TINY, remat=False)
    params = gpt2.init_params(cfg, seed=7)
    params["wpe"] = params["wpe"] * 40.0
    eng = deepspeed_tpu.init_inference(
        model_config=cfg, params=params, dtype=jnp.float32,
        max_out_tokens=cfg.n_positions,
    )
    srv = ServingEngine(
        eng, num_slots=2, prefill_chunk=8, max_len=64,
        journal_dir=journal_dir,
        tenants={
            "enabled": True,
            # unlimited default bucket (rate 0 + burst 0); one tenant
            # starved to a 1-token burst for the 429 probe
            "overrides": {
                "starved": {"refill_tokens_per_second": 0.001,
                            "burst_tokens": 1.0},
            },
        },
    )
    return cfg, eng, srv


# ---------------------------------------------------------------------------
# server child
# ---------------------------------------------------------------------------

def run_server(journal_dir, port_file):
    from deepspeed_tpu.resilience import faults

    faults.install_from_env(rank=0)

    from deepspeed_tpu.serving.frontdoor.http import FrontDoor

    _, _, srv = make_engine(journal_dir)
    replayed = srv.recover()
    if replayed:
        log(f"server: replayed {len(replayed)} request(s): {replayed}")
    srv.install_watchdog()
    fd = FrontDoor(srv, host="127.0.0.1", port=0)
    fd._bind()
    tmp = port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(fd.port))
    os.rename(tmp, port_file)  # atomic: the parent never reads a torn port
    fd._pump()  # main thread: the watchdog's SystemExit(43) unwinds here


# ---------------------------------------------------------------------------
# parent-side HTTP client
# ---------------------------------------------------------------------------

def wait_port(port_file, proc, timeout=300):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(port_file):
            with open(port_file) as f:
                return int(f.read())
        if proc.poll() is not None:
            raise RuntimeError(f"server died during boot rc={proc.poll()}")
        time.sleep(0.1)
    raise RuntimeError("server never published its port")


def post(port, body, timeout=300):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/generate", json.dumps(body),
                 {"Content-Type": "application/json"})
    return conn, conn.getresponse()


def read_stream(resp):
    """Read JSON lines off a chunked response until the terminating
    chunk, EOF, or a torn connection.  Returns (tokens, request_id,
    done) — ``done`` False means the stream was cut mid-flight."""
    tokens, rid, done = [], None, False
    try:
        while True:
            line = resp.readline()
            if not line:
                break
            rec = json.loads(line)
            if "request_id" in rec:
                rid = rec["request_id"]
            if "tokens" in rec:
                tokens.extend(rec["tokens"])
            if rec.get("done"):
                done = True
                break
    except (http.client.IncompleteRead, http.client.HTTPException,
            ConnectionResetError, OSError, json.JSONDecodeError):
        pass
    return tokens, rid, done


def spawn_server(journal_dir, port_file, fault_plan=None):
    env = dict(os.environ)
    env.pop("DS_FAULT_PLAN", None)
    if fault_plan is not None:
        env["DS_FAULT_PLAN"] = fault_plan
    if os.path.exists(port_file):
        os.unlink(port_file)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--role", "server",
         "--journal", journal_dir, "--port-file", port_file, "--dryrun"],
        env=env,
    )
    return proc, wait_port(port_file, proc)


# ---------------------------------------------------------------------------
# the proof
# ---------------------------------------------------------------------------

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dryrun", action="store_true", help="tiny model on CPU")
    ap.add_argument("--role", default=None, choices=(None, "server"))
    ap.add_argument("--journal", default=None)
    ap.add_argument("--port-file", default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.role == "server":
        run_server(args.journal, args.port_file)
        return

    import numpy as np

    from deepspeed_tpu.resilience.faults import plan_json
    from deepspeed_tpu.serving.frontdoor.tenants import journal_tenant_totals

    t0 = time.monotonic()
    rng = np.random.default_rng(args.seed)
    with tempfile.TemporaryDirectory(prefix="frontdoor_chaos_") as root:
        journal = os.path.join(root, "journal")
        port_file = os.path.join(root, "port")

        # the deterministic-serving bar: solo generate() of each prompt
        cfg, eng, _ = make_engine(os.path.join(root, "ref-journal"))
        warm_p = rng.integers(1, cfg.vocab_size, 6, dtype=np.int32)
        kill_p = rng.integers(1, cfg.vocab_size, 8, dtype=np.int32)
        drain_p = rng.integers(1, cfg.vocab_size, 8, dtype=np.int32)
        expect_kill = [int(t) for t in np.asarray(
            eng.generate(kill_p[None, :], max_new_tokens=MAX_NEW)
        )[0]][len(kill_p):]
        expect_drain = [int(t) for t in np.asarray(
            eng.generate(drain_p[None, :], max_new_tokens=DRAIN_MAX_NEW)
        )[0]][len(drain_p):]

        # ---- phase 1+2: armed server; warmup, throttle probe, kill -9
        plan = plan_json([
            {"site": "frontdoor.stream", "action": "sigkill",
             "after": KILL_AFTER_CHUNKS},
        ])
        proc, port = spawn_server(journal, port_file, fault_plan=plan)
        conn, resp = post(port, {
            "prompt": [int(t) for t in warm_p], "max_new_tokens": 4,
            "tenant": "warm", "client_key": "fd-warm",
        })
        warm_out = json.loads(resp.read())
        conn.close()
        if warm_out.get("finish_reason") not in ("eos", "length"):
            log(f"warmup failed: {warm_out}")
            sys.exit(1)
        warm_tokens = len(warm_out["tokens"])

        conn, resp = post(port, {
            "prompt": [int(t) for t in warm_p], "max_new_tokens": 4,
            "tenant": "starved",
        })
        throttle_body = json.loads(resp.read())
        throttle_status = resp.status
        throttle_ra = resp.getheader("Retry-After")
        conn.close()
        if (throttle_status != 429 or throttle_ra is None
                or throttle_body.get("type") != "TenantThrottled"):
            log(f"starved tenant probe: want 429 + Retry-After + "
                f"TenantThrottled, got {throttle_status} ra={throttle_ra} "
                f"{throttle_body}")
            sys.exit(1)
        log(f"starved tenant throttled: 429, Retry-After={throttle_ra}s")

        conn, resp = post(port, {
            "prompt": [int(t) for t in kill_p], "max_new_tokens": MAX_NEW,
            "tenant": "acme", "client_key": "fd-kill", "stream": True,
        })
        prefix, rid1, done = read_stream(resp)
        conn.close()
        rc1 = proc.wait(timeout=60)
        if done or rc1 != -signal.SIGKILL:
            log(f"kill -9 leg: stream done={done} rc={rc1}, expected a cut "
                f"stream and rc={-signal.SIGKILL}")
            sys.exit(1)
        log(f"server SIGKILL'd mid-stream (rc={rc1}) after "
            f"{len(prefix)} observed token(s), request id {rid1}")

        # ---- phase 3: recover; same client_key -> same id, full stream
        proc, port = spawn_server(journal, port_file)
        conn, resp = post(port, {
            "prompt": [int(t) for t in kill_p], "max_new_tokens": MAX_NEW,
            "tenant": "acme", "client_key": "fd-kill", "stream": True,
        })
        full, rid2, done = read_stream(resp)
        conn.close()
        if not done:
            log("post-recovery stream never finished")
            sys.exit(1)
        if rid2 != rid1:
            log(f"at-most-once VIOLATED: request id {rid1} -> {rid2} across "
                "the crash (client_key re-admitted)")
            sys.exit(1)
        if full[:len(prefix)] != prefix:
            log(f"stream NOT prefix-consistent across recovery: "
                f"observed {prefix}, replayed {full[:len(prefix)]}")
            sys.exit(1)
        if full != expect_kill:
            log(f"replayed stream DIVERGED from solo generate(): "
                f"{full} != {expect_kill}")
            sys.exit(1)
        log(f"recovery: same id {rid2}, {len(full)} token(s) streamed, "
            "prefix-consistent + bit-identical to solo")

        # ---- phase 4: SIGTERM mid-stream -> drain, 503, exit 43
        conn, resp = post(port, {
            "prompt": [int(t) for t in drain_p],
            "max_new_tokens": DRAIN_MAX_NEW,
            "tenant": "acme2", "client_key": "fd-drain", "stream": True,
        })
        # SIGTERM must land while the request is genuinely IN-FLIGHT
        # (slot-resident): a merely-queued request does not drain — it
        # replays from the journal.  Read past the request_id chunk
        # until the first token delta proves admission.
        pre = []
        while not pre:
            rec = json.loads(resp.readline())
            if "tokens" in rec:
                pre.extend(rec["tokens"])
        os.kill(proc.pid, signal.SIGTERM)
        probe_status, probe_ra, probe_type = None, None, None
        try:
            c2, r2 = post(port, {
                "prompt": [int(t) for t in warm_p], "max_new_tokens": 4,
                "tenant": "warm",
            }, timeout=30)
            probe_status = r2.status
            probe_ra = r2.getheader("Retry-After")
            probe_type = json.loads(r2.read()).get("type")
            c2.close()
        except OSError as e:
            log(f"drain probe connection failed ({e!r}) — drain won the race")
        tail, _, done = read_stream(resp)
        drained = pre + tail
        conn.close()
        rc2 = proc.wait(timeout=120)
        if not done:
            log("SIGTERM cut the in-flight stream — drain must stream it out")
            sys.exit(1)
        if drained != expect_drain:
            log(f"drained stream DIVERGED: {drained} != {expect_drain}")
            sys.exit(1)
        if rc2 != 43:
            log(f"server exit rc={rc2}, expected 43 (journal-committed drain)")
            sys.exit(1)
        if probe_status is not None and (
                probe_status != 503 or probe_ra is None
                or probe_type != "ServingDraining"):
            log(f"drain probe: want 503 + Retry-After + ServingDraining, got "
                f"{probe_status} ra={probe_ra} type={probe_type}")
            sys.exit(1)
        log(f"SIGTERM: in-flight stream completed ({len(drained)} tokens), "
            f"probe={'503' if probe_status else 'n/a'}, exit rc=43")

        # ---- phase 5: per-tenant accounting reconciles with the journal
        totals = journal_tenant_totals(journal)
        observed = {
            "warm": warm_tokens,
            "acme": len(full),
            "acme2": len(drained),
        }
        for tn, n in observed.items():
            row = totals.get(tn)
            if row is None or row["admitted"] != 1:
                log(f"tenant {tn}: want exactly 1 admission, got {row}")
                sys.exit(1)
            if row["billed_tokens"] != n:
                log(f"tenant {tn}: journal billed {row['billed_tokens']} "
                    f"token(s), client observed {n} — accounting broke")
                sys.exit(1)
        log(f"accounting reconciled: {observed} billed exactly once each")

    record = {
        "metric": "frontdoor_chaos_kill9_stream_resume",
        "value": len(full),
        "unit": "tokens_streamed_bit_identical",
        "observed_prefix": len(prefix),
        "victim_rc": rc1,
        "drain_rc": rc2,
        "throttle_status": throttle_status,
        "drain_probe_status": probe_status,
        "tenants_reconciled": len(observed),
        "wall_s": round(time.monotonic() - t0, 1),
    }
    print(json.dumps(record), flush=True)
    log(
        f"OK: kill -9 mid-stream -> same-id resume, bit-identical "
        f"continuation; SIGTERM -> drained stream + 503 + exit 43; "
        f"{len(observed)} tenants reconciled ({record['wall_s']}s)"
    )


if __name__ == "__main__":
    main()
