"""Measure dense-psum vs sparse (rows+ids allgather) embedding-gradient
exchange at GPT-2 shapes — the in-graph analog of the reference's CSR
embedding gradients (``runtime/csr_tensor.py`` + ``engine.py:1559``
``csr_allreduce``), which this framework deliberately does NOT run
in-graph (VERDICT r3 #10 asks for the decision to be measured and
written down; the conclusion lives in docs/design-notes.md).

Two exchange formulations for the wte gradient under data parallelism:

  dense:  every rank psums the full (V, D) scatter-added gradient —
          what the engine's compiled step does today (the embedding
          grad rides the same psum/reduce-scatter as every other grad).
  sparse: every rank all-gathers its (B·T, D) token-grad rows + ids and
          scatter-adds the gathered rows into the dense (V, D) buffer
          locally — wire ∝ tokens instead of vocab (the reference's CSR
          motivation), compute adds a (dp·B·T)-row scatter.

Run on the 8-device CPU mesh for HLO wire bytes; on TPU it times the
local scatter-add the sparse form adds.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.comm.mesh import make_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.utils.hlo import collective_bytes

    V, D = 50257, 768  # GPT-2 small vocab/emb
    BT = 4 * 1024      # per-rank tokens (micro_bs 4 × seq 1024)
    n = jax.device_count()
    on_tpu = jax.default_backend() in ("tpu", "axon")
    mesh = make_mesh(MeshConfig(data=n))
    rows_sh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    rng = np.random.default_rng(0)
    ids = jax.device_put(rng.integers(0, V, (n * BT,), dtype=np.int32), rows_sh)
    rows = jax.device_put(rng.standard_normal((n * BT, D)).astype(np.float32), rows_sh)

    def dense_exchange(ids, rows):
        # per-rank scatter-add to dense, then psum (what grad-psum does)
        g = jnp.zeros((V, D), jnp.float32).at[ids].add(rows)
        return jax.lax.with_sharding_constraint(g, rep)

    def sparse_exchange(ids, rows):
        # allgather rows+ids (already sharded → constraint to replicated
        # inserts the gather), then ONE local scatter-add
        ids_full = jax.lax.with_sharding_constraint(ids, rep)
        rows_full = jax.lax.with_sharding_constraint(rows, rep)
        return jnp.zeros((V, D), jnp.float32).at[ids_full].add(rows_full)

    d_txt = jax.jit(dense_exchange).lower(ids, rows).compile().as_text()
    s_txt = jax.jit(sparse_exchange).lower(ids, rows).compile().as_text()
    d_bytes, s_bytes = collective_bytes(d_txt), collective_bytes(s_txt)
    print(f"devices={n}  V·D dense grad = {V*D*4/1e6:.1f} MB, per-rank rows = {BT*D*4/1e6:.1f} MB")
    print(f"dense-psum wire bytes:  {d_bytes/1e6:10.1f} MB")
    print(f"sparse-gather wire:     {s_bytes/1e6:10.1f} MB   ({d_bytes/max(s_bytes,1):.1f}x less)")

    if on_tpu:
        # the sparse form's added local cost: scatter-add of n·BT rows
        f = jax.jit(lambda i, r: jnp.zeros((V, D), jnp.float32).at[i].add(r))
        i1 = jnp.asarray(np.asarray(ids))
        r1 = jnp.asarray(np.asarray(rows))
        _ = np.asarray(f(i1, r1)[0, 0])
        t0 = time.time()
        for _ in range(10):
            o = f(i1, r1)
        _ = np.asarray(o[0, 0])
        print(f"TPU scatter-add of {n*BT} rows into ({V},{D}): {(time.time()-t0)/10*1000:.2f} ms")


if __name__ == "__main__":
    main()
