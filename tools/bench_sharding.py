"""Weight-update-sharding sweep: replicated vs cross-replica ZeRO-1.

Drives the `sharding` bench rung (bench.py) and runs standalone:

    python tools/bench_sharding.py --dryrun      # 8 virtual CPU devices
    python tools/bench_sharding.py --steps 16    # real devices

Sweeps the optimizer-update phase (docs/sharding.md) on a GPT-2 config
(124M on TPU, tiny on the CPU dryrun) across three placements:

* ``replicated`` — classic GSPMD ZeRO-0-style update: every replica
  recomputes the full update over replicated optimizer state;
* ``cross-replica`` — arXiv:2004.13336 weight-update sharding, the
  default at ``zero_optimization.stage >= 1``: state + update sharded
  along ``data``, one params-sized all-gather of updated values;
* ``cross-replica x fsdp`` — the composed ``data x fsdp`` grid
  (``add_update_axis`` fsdp-major placement), when devices allow.

Each record carries the MEASURED update-phase costs next to the
analytic model so regressions in either are visible:

* ``update_flops_per_replica`` / ``update_bytes_per_replica`` —
  compiled cost analysis of the engine's ``_apply_update`` phase alone
  (the same probe tests/test_sharding.py pins the ~dp x ratio with);
* ``opt_state_bytes_per_replica`` — addressable-shard bytes of the
  live optimizer state (vs ``opt_state_bytes_total``);
* ``update_allgather_bytes_hlo`` — all-gather wire bytes parsed from
  the compiled train executable (sharded pays one params-sized gather,
  replicated pays none);
* ``model`` — :func:`deepspeed_tpu.sharding.weight_update_model`;
* ``steps_per_s``, the loss trajectory (parity vs replicated), and
  ``compiles`` (must be 1: the sharded update is one executable).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# --dryrun must win before jax initializes (same recipe as tests/conftest.py)
if "--dryrun" in sys.argv:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def log(msg):
    print(f"[bench_sharding] {msg}", file=sys.stderr, flush=True)


def emit(rec):
    print(json.dumps(rec), flush=True)
    from deepspeed_tpu.telemetry.regression import tool_history_emit

    # standalone runs feed the persistent bench history too (no-op when
    # the bench.py driver parent is the history writer)
    tool_history_emit(rec, rung="sharding",
                      base_dir=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _opt_state_bytes(engine):
    import jax

    leaves = [
        l for l in jax.tree.leaves(engine.state["opt_state"]) if hasattr(l, "addressable_shards")
    ]
    per_dev = sum(l.addressable_shards[0].data.nbytes for l in leaves)
    total = sum(l.nbytes for l in leaves)
    return per_dev, total


def _update_phase_cost(engine):
    """Compiled cost analysis of the update phase ALONE — grads in,
    updated state out — so the numbers isolate exactly what
    cross-replica sharding claims to cut."""
    import jax
    import jax.numpy as jnp

    grads = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), engine.state["params"])
    compiled = jax.jit(lambda s, g: engine._apply_update(s, g)).lower(engine.state, grads).compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    return float(ca.get("flops", 0.0)), float(ca.get("bytes accessed", 0.0))


def _train_allgather_bytes(engine):
    from deepspeed_tpu.utils.hlo import collective_bytes_by_op

    keys = [k for k in engine._compiled if isinstance(k, tuple) and k[0] == "train_batch"]
    if not keys:
        return 0
    return collective_bytes_by_op(engine._compiled[keys[0]].as_text()).get("all-gather", 0)


def sweep(steps: int, on_tpu: bool):
    import dataclasses

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.sharding import weight_update_model

    n_dev = jax.device_count()
    cfg = (
        dataclasses.replace(gpt2.GPT2_SMALL, remat=False, scan_unroll=gpt2.GPT2_SMALL.n_layer)
        if on_tpu
        else dataclasses.replace(gpt2.GPT2_TINY, n_layer=4, n_embd=64, n_head=4, vocab_size=256)
    )
    micro_bs, seq = (8, 1024) if on_tpu else (1, 32)
    model_fn, init_fn, _ = gpt2.make_model(cfg)
    init = init_fn()

    def batches(n, global_bs):
        r = np.random.default_rng(1)  # same data per placement
        for _ in range(n):
            yield {"input_ids": r.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)}

    runs = [
        ("replicated", {"data": n_dev}, 1, False),
        ("cross-replica", {"data": n_dev}, 1, True),
    ]
    if n_dev >= 4 and n_dev % 2 == 0:
        runs.append(("cross-replica-fsdp", {"data": 2, "fsdp": n_dev // 2}, 2, True))

    base = None  # the replicated baseline record
    for name, mesh, stage, cross in runs:
        config = {
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": stage, "cross_replica_weight_update": cross},
            "mesh": mesh,
            "steps_per_print": 100000,
        }
        try:
            init_copy = jax.tree.map(np.copy, init)
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model_fn, model_parameters=init_copy, config=config
            )
            global_bs = micro_bs * engine.mesh_info.dp_world_size
            losses = [float(engine.train_batch(b)) for b in batches(2, global_bs)]  # warm
            t0 = time.time()
            losses += [float(engine.train_batch(b)) for b in batches(steps, global_bs)]
            dt = (time.time() - t0) / steps
        except Exception as e:  # noqa: BLE001 — one failed placement must not kill the sweep
            log(f"[{name}] FAILED: {str(e)[:300]}")
            emit({"metric": f"weight_update_{name}", "skipped": True, "reason": str(e)[:300]})
            continue

        dp = engine.mesh_info.dp_world_size
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(engine.state["params"]))
        flops, bytes_ = _update_phase_cost(engine)
        per_dev, total = _opt_state_bytes(engine)
        rec = {
            "metric": f"weight_update_{name}",
            "value": round(1.0 / dt, 3),
            "unit": "steps/s",
            "dp": dp,
            "n_params": n_params,
            "update_flops_per_replica": int(flops),
            "update_bytes_per_replica": int(bytes_),
            "opt_state_bytes_per_replica": int(per_dev),
            "opt_state_bytes_total": int(total),
            "update_allgather_bytes_hlo": int(_train_allgather_bytes(engine)),
            "model": weight_update_model(n_params, dp, sharded=cross),
            "final_loss": round(losses[-1], 5),
            "losses": [round(l, 5) for l in losses],
            "compiles": engine.compilation_count,
            "micro_bs": micro_bs,
            "seq": seq,
        }
        if name == "replicated":
            base = rec
        elif base is not None and base["dp"] == dp:
            rec["update_flops_reduction_vs_replicated"] = round(
                base["update_flops_per_replica"] / max(rec["update_flops_per_replica"], 1), 2
            )
            rec["opt_state_bytes_reduction_vs_replicated"] = round(
                base["opt_state_bytes_per_replica"] / max(rec["opt_state_bytes_per_replica"], 1), 2
            )
            pairs = list(zip(rec["losses"], base["losses"]))
            rec["loss_rel_dev_vs_replicated"] = round(
                float(np.mean([abs(a - b) / (abs(b) + 1e-9) for a, b in pairs])), 4
            )
        log(
            f"[{name}] steps/s={rec['value']} update_flops/replica={int(flops):,} "
            f"opt_bytes/replica={per_dev:,} (total {total:,}) compiles={rec['compiles']}"
        )
        emit(rec)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dryrun", action="store_true", help="8 virtual CPU devices (handled pre-import)")
    ap.add_argument("--steps", type=int, default=None)
    args = ap.parse_args()

    import jax

    on_tpu = jax.default_backend() in ("tpu", "axon")
    steps = args.steps if args.steps is not None else (12 if on_tpu else 4)
    log(f"backend={jax.default_backend()} devices={jax.device_count()} steps={steps}")
    sweep(steps, on_tpu)


if __name__ == "__main__":
    main()
