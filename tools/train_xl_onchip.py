"""Train GPT-2 XL (1.5B — the BASELINE.json north-star model) on ONE
chip via the ZeRO-Infinity streaming executor: HBM holds one layer
group + boundary activations; fp32 masters + Adam moments live on the
host (reference capability row: 13B on one 32GB device,
docs/_pages/features.md:116, partitioned_param_swapper.py:36).

On the tunneled dev chip the host<->device link (not the chip) bounds
step time — this run is the CAPABILITY proof for the north-star model;
throughput at this scale needs a real PCIe-class host link or fsdp>=2
(see bench.py's note).  Prints per-step loss/time + a JSON record.

Run: python tools/train_xl_onchip.py [steps] [seq] [micro_bs] [buffer_count]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 2
    seq = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    mb = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    lpg = int(sys.argv[4]) if len(sys.argv) > 4 else 8

    cfg = gpt2.GPT2_XL
    model_fn, init_fn, _ = gpt2.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": mb,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "cpu", "buffer_count": lpg},
            "offload_optimizer": {"device": "cpu"},
        },
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    t0 = time.time()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config
    )
    print(f"init {time.time()-t0:.0f}s  engine={type(engine).__name__}", flush=True)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (mb, seq), dtype=np.int32)}
    losses, times = [], []
    for s in range(steps):
        t0 = time.time()
        loss = float(engine.train_batch(batch))
        dt = time.time() - t0
        losses.append(loss)
        times.append(dt)
        print(f"step {s}: loss={loss:.4f}  {dt:.0f}s", flush=True)

    # one SERIALIZED step with per-phase sync: attributes wall time to
    # host-link upload vs chip compute vs grad drain vs host Adam (the
    # r4 steady-state decomposition — overlaps removed, so the phase sum
    # exceeds a normal pipelined step's wall time)
    timing = {}
    t0 = time.time()
    loss = float(engine.train_batch(batch, timing=timing))
    timing["total_serialized_s"] = time.time() - t0
    losses.append(loss)
    print("profiled step: " + "  ".join(f"{k}={v:.1f}s" for k, v in timing.items()), flush=True)

    rec = {
        "metric": "gpt2_xl_1p5b_single_chip_streaming_train",
        "params_m": round(cfg.num_params() / 1e6, 1),
        "losses": [round(l, 4) for l in losses],
        "step_seconds": [round(t, 1) for t in times],
        "step_breakdown_serialized": {k: round(v, 1) for k, v in timing.items()},
        "seq": seq,
        "micro_bs": mb,
        "engine": type(engine).__name__,
        "note": "steady-state streaming record on one tunneled v5e: HBM holds "
        "one layer group; the serialized-step breakdown attributes wall time "
        "to host-link upload / chip compute / grad drain / host Adam "
        "(pipelined steps overlap these, so their wall < breakdown sum)",
    }
    print("RESULT " + json.dumps(rec), flush=True)
    # capability records live in their own file — bench.py clears
    # BENCH_EXTRA.json at the start of every run
    import bench

    bench.append_capability_record(rec)


if __name__ == "__main__":
    main()
