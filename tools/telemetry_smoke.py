"""Telemetry end-to-end smoke (CI `telemetry` job; docs/telemetry.md).

One process, dryrun CPU mesh (8 virtual devices):

1. arm the plane (registry + jsonl/prometheus sinks + Chrome-trace
   buffer), run a few dryrun train steps, check the MFU / flops /
   HBM gauges are live;
2. run a serving engine through a handful of requests;
3. export ``trace.json`` and validate it against the Chrome trace-event
   schema (the same :func:`validate_chrome_trace` the tests gate on),
   checking the per-request span lanes exist;
4. scrape the Prometheus textfile and assert the expected families.

Exit 0 on success; any failed check raises.
"""
from __future__ import annotations

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def log(msg):
    print(f"[telemetry_smoke] {msg}", file=sys.stderr, flush=True)


def main(out_dir: str) -> int:
    import dataclasses

    import jax

    jax.config.update("jax_platforms", "cpu")

    import deepspeed_tpu
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.serving import ServingEngine
    from deepspeed_tpu.telemetry import validate_chrome_trace

    os.makedirs(out_dir, exist_ok=True)
    trace_path = os.path.join(out_dir, "trace.json")

    # -- 1) dryrun train with the full plane armed via the config block --
    cfg = dataclasses.replace(gpt2.GPT2_TINY, remat=False, scan_unroll=gpt2.GPT2_TINY.n_layer)
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 2,
        "telemetry": {
            "enabled": True,
            "exporters": ["jsonl", "prometheus"],
            "export_interval_seconds": 60,  # we flush() explicitly
            "output_path": out_dir,
            "trace": True,
            "trace_path": trace_path,
        },
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config, tp_spec_fn=tp_fn
    )
    batch = {"input_ids": np.zeros((16, 16), np.int32)}
    for _ in range(4):
        engine.train_batch(batch)
    summ = engine.telemetry.summary()
    assert summ["mfu"] is not None and summ["mfu"] > 0, f"MFU gauge not live: {summ}"
    assert summ["hbm_bytes_per_step"], f"HBM gauge not live: {summ}"
    log(f"train gauges live: mfu={summ['mfu']} hbm={summ['hbm_bytes_per_step']}")

    # -- 2) a serving run over the same armed plane ----------------------
    inf = deepspeed_tpu.init_inference(model="tiny", max_out_tokens=128)
    srv = ServingEngine(inf, num_slots=2, prefill_chunk=16, max_len=64)
    rng = np.random.default_rng(0)
    for _ in range(4):
        srv.submit(rng.integers(1, 100, 24, dtype=np.int32), max_new_tokens=4)
    finished = srv.drain(max_steps=10_000)
    assert len(finished) == 4, f"serving drain incomplete: {len(finished)}"
    log(f"serving drained {len(finished)} requests")

    # -- 3) trace.json: schema-valid, request lanes present --------------
    telemetry.export_trace(trace_path)
    doc = json.load(open(trace_path))
    problems = validate_chrome_trace(doc)
    assert not problems, f"trace schema problems: {problems[:10]}"
    names = {e["name"] for e in doc["traceEvents"]}
    for want in ("train/compile", "serving/decode", "queue", "prefill", "decode", "retire"):
        assert want in names, f"expected span '{want}' missing; have {sorted(names)}"
    req_lanes = {
        e["tid"] for e in doc["traceEvents"]
        if e.get("pid") == telemetry.PID_REQUESTS and e["ph"] == "X"
    }
    assert len(req_lanes) >= 4, f"expected >=4 request lanes, got {req_lanes}"
    log(f"trace.json schema-valid: {len(doc['traceEvents'])} events, "
        f"{len(req_lanes)} request lanes")

    # -- 4) Prometheus textfile scrape -----------------------------------
    telemetry.flush()
    prom = open(os.path.join(out_dir, "metrics_rank0.prom")).read()
    for family in ("ds_mfu", "ds_train_step_wall_ms", "ds_serving_ttft_ms_count",
                   "ds_comm_bytes_per_step"):
        assert family in prom, f"prometheus family '{family}' missing"
    jsonl = open(os.path.join(out_dir, "metrics_rank0.jsonl")).read().strip().splitlines()
    assert jsonl and json.loads(jsonl[-1])["metrics"], "jsonl export empty"
    log(f"prometheus + jsonl sinks verified ({len(prom.splitlines())} prom lines, "
        f"{len(jsonl)} jsonl exports)")
    print("telemetry smoke OK")
    return 0


if __name__ == "__main__":
    import tempfile

    out = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(prefix="ds_telemetry_smoke_")
    sys.exit(main(out))
