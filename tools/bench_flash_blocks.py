"""Micro-bench flash attention fwd+bwd block sizes at a given shape.

Run: python tools/bench_flash_blocks.py [B H T D]
Prints ms per fwd+bwd for each (block_q, block_k) combo — the tuning
data behind the per-shape block choices in flash_attention.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from deepspeed_tpu.ops.attention.flash_attention import flash_attention

    B = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    H = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    T = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
    D = int(sys.argv[4]) if len(sys.argv) > 4 else 64
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)) * 0.1, jnp.bfloat16) for _ in range(3))

    flops = 4 * B * H * T * T * D / 2 * 3.5  # causal fwd (x1) + FA2 bwd (~x2.5)
    results = []
    for bq in (1024, 512, 256, 128):
        for bk in (1024, 512, 256, 128):
            if bq > T or bk > T:
                continue

            def f(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk) ** 2
                )

            # chain iterations through a data dependency, end with a true
            # host fetch, and DIFFERENCE two chain lengths — the tunnel
            # adds ~100ms fixed RTT per dispatch that would otherwise
            # swamp sub-ms kernels (block_until_ready is not a barrier
            # on tunneled backends)
            def chain(length):
                def many(q, k, v):
                    def body(c, _):
                        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(c, k, v)
                        return c + 1e-6 * dq.astype(c.dtype), (jnp.sum(dk) + jnp.sum(dv)).astype(jnp.float32)

                    c, s = jax.lax.scan(body, q, None, length=length)
                    return jnp.sum(c).astype(jnp.float32) + jnp.sum(s)

                return jax.jit(many)

            try:
                m_short, m_long = chain(20), chain(120)
                float(m_short(q, k, v))
                float(m_long(q, k, v))  # compile + warm both
                t0 = time.time()
                float(m_short(q, k, v))
                t_short = time.time() - t0
                t0 = time.time()
                float(m_long(q, k, v))
                t_long = time.time() - t0
                dt = (t_long - t_short) / 100
            except Exception as e:
                print(f"bq={bq:5d} bk={bk:5d}  FAILED {str(e)[:80]}")
                continue
            tf = flops / dt / 1e12
            results.append((dt, bq, bk))
            print(f"bq={bq:5d} bk={bk:5d}  {dt*1e3:7.2f} ms  ~{tf:5.1f} TFLOP/s")
    results.sort()
    print("best:", results[0] if results else None)


if __name__ == "__main__":
    main()
