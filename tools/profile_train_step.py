"""Profile one compiled train step on the real chip: per-source /
per-HLO-category / top-op device-time attribution (the tool behind the
MFU work — it exposed the fp32-dot flash kernels, the scan bookkeeping,
and the per-line TFLOP/s of every matmul).

The cost walk itself lives in ``deepspeed_tpu.telemetry.attribution``
(shared with profile_bert_step.py / profile_decode.py); this script is
the GPT-2 harness around it, plus the compile-time roofline table from
the executable's own HLO.

Run: python tools/profile_train_step.py [preset] [micro_bs] [gas] [seq]
"""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.runtime.engine import _PlacedBatch
    from deepspeed_tpu.telemetry.attribution import (
        format_trace_tables,
        profile_and_report,
    )

    preset = sys.argv[1] if len(sys.argv) > 1 else "gpt2"
    mb = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    gas = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    seq = int(sys.argv[4]) if len(sys.argv) > 4 else 1024
    steps = 3

    if preset.startswith("sweep:"):
        # profile one of the 774M sweep configurations by name
        from tools.sweep_774m import CONFIGS

        c = CONFIGS[preset.split(":", 1)[1]]
        cfg = dataclasses.replace(gpt2.GPT2_LARGE, **c["model"])
        mb, gas = c["mb"], c["gas"]
        opt_extra = c.get("opt") or {}
    else:
        cfg = dataclasses.replace(gpt2.PRESETS[preset], remat=False)
        opt_extra = {}
    seq = min(seq, cfg.n_positions)
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": mb,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3 if preset.startswith("sweep:") else 0},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4, **opt_extra}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config, tp_spec_fn=tp_fn
    )
    rng = np.random.default_rng(0)
    placed = _PlacedBatch(
        engine._stack_and_place(
            {"input_ids": rng.integers(0, cfg.vocab_size, (mb * gas, seq), dtype=np.int32)}
        )
    )
    loss = engine.train_batch(placed)
    float(loss)  # true sync (block_until_ready is unreliable on tunnels)

    def one_step():
        nonlocal loss
        loss = engine.train_batch(placed)

    tables = profile_and_report(one_step, steps=steps, sync=lambda: float(loss))
    print(format_trace_tables(tables, unit="step"))

    # compile-time roofline view from the executable's own HLO — the
    # same table the telemetry plane publishes as attribution/* gauges
    attr = engine.train_step_attribution()
    if attr is not None:
        print()
        print(attr.format_table())


if __name__ == "__main__":
    main()
