"""Profile one compiled train step on the real chip and print per-source
device-time attribution (the tool behind this round's MFU work: it
exposed the fp32-dot flash kernels, the scan bookkeeping, and the
per-line TFLOP/s of every matmul).

Run: python tools/profile_train_step.py [preset] [micro_bs] [gas] [seq]
"""
import collections
import dataclasses
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.runtime.engine import _PlacedBatch

    preset = sys.argv[1] if len(sys.argv) > 1 else "gpt2"
    mb = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    gas = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    seq = int(sys.argv[4]) if len(sys.argv) > 4 else 1024
    steps = 3

    if preset.startswith("sweep:"):
        # profile one of the 774M sweep configurations by name
        from tools.sweep_774m import CONFIGS

        c = CONFIGS[preset.split(":", 1)[1]]
        cfg = dataclasses.replace(gpt2.GPT2_LARGE, **c["model"])
        mb, gas = c["mb"], c["gas"]
    else:
        cfg = dataclasses.replace(gpt2.PRESETS[preset], remat=False)
    seq = min(seq, cfg.n_positions)
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    opt_extra = {}
    if preset.startswith("sweep:"):
        from tools.sweep_774m import CONFIGS as _C

        opt_extra = _C[preset.split(":", 1)[1]].get("opt") or {}
    config = {
        "train_micro_batch_size_per_gpu": mb,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3 if preset.startswith("sweep:") else 0},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4, **opt_extra}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config, tp_spec_fn=tp_fn
    )
    rng = np.random.default_rng(0)
    placed = _PlacedBatch(
        engine._stack_and_place(
            {"input_ids": rng.integers(0, cfg.vocab_size, (mb * gas, seq), dtype=np.int32)}
        )
    )
    loss = engine.train_batch(placed)
    float(loss)  # true sync (block_until_ready is unreliable on tunnels)

    trace_dir = tempfile.mkdtemp(prefix="tpu_trace_")
    with jax.profiler.trace(trace_dir):
        for _ in range(steps):
            loss = engine.train_batch(placed)
        float(loss)

    f = sorted(glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")))[-1]
    with gzip.open(f) as fh:
        data = json.load(fh)
    ev = [
        e
        for e in data["traceEvents"]
        if e.get("ph") == "X" and e.get("args") and e["args"].get("hlo_category")
    ]
    src_t = collections.Counter()
    src_f = collections.Counter()
    for e in ev:
        if e["args"]["hlo_category"] in ("while", "conditional", "call"):
            continue
        s = e["args"].get("source", "?")
        src_t[s] += e["dur"]
        src_f[s] += int(e["args"].get("model_flops", 0) or 0)
    print(f"{'source':68s} {'ms/step':>8s} {'TFLOP/s':>8s}")
    for s, t in src_t.most_common(20):
        tf = src_f[s] / (t * 1e-6) / 1e12 if t else 0
        print(f"{s[-68:]:68s} {t/1e3/steps:8.1f} {tf:8.1f}")

    # HLO-category view (dot vs fusion vs copy/convert traffic) and the
    # top individual ops — separates "matmuls running slow" from
    # "non-matmul time attributed to the same source line"
    cat_t = collections.Counter()
    cat_f = collections.Counter()
    op_t = collections.Counter()
    for e in ev:
        c = e["args"]["hlo_category"]
        if c in ("while", "conditional", "call"):
            continue
        cat_t[c] += e["dur"]
        cat_f[c] += int(e["args"].get("model_flops", 0) or 0)
        op_t[e.get("name", "?")[:70]] += e["dur"]
    print(f"\n{'hlo category':30s} {'ms/step':>8s} {'TFLOP/s':>8s}")
    for c, t in cat_t.most_common(12):
        tf = cat_f[c] / (t * 1e-6) / 1e12 if t else 0
        print(f"{c:30s} {t/1e3/steps:8.1f} {tf:8.1f}")
    print(f"\n{'top ops':70s} {'ms/step':>8s}")
    for o, t in op_t.most_common(15):
        print(f"{o:70s} {t/1e3/steps:8.1f}")


if __name__ == "__main__":
    main()
