"""Request-level serving SLO bench: seeded Poisson arrivals against the
continuous-batching engine (docs/serving.md).

Drives the `serving` bench rung (bench.py) and runs standalone:

    python tools/bench_serving.py --dryrun        # tiny model, CPU
    python tools/bench_serving.py                 # gpt2-xl on the chip

Per (kv dtype, offered load) it emits ONE record in the bench schema:

* ``value`` — end-to-end generated tokens/s over the run's makespan;
* ``ttft_p50_ms / ttft_p99_ms`` — time-to-first-token from the request's
  *scheduled* arrival (queue wait + chunked prefill included);
* ``tpot_p50_ms / tpot_p99_ms`` — per-output-token decode latency
  ((finish - first token) / (generated - 1));
* ``prefill_ms / decode_ms / sched_ms / queue_depth`` — the serving
  timeline's per-step phase attribution and mean queue depth.

Arrivals are a seeded Poisson process (exponential inter-arrivals);
offered loads are fractions of the measured closed-loop service rate, so
0.5x is comfortably under capacity and 2.0x is a sustained overload that
exercises queueing (and, with ``--max-queue``, rejection).  All timing
is host wall-clock around ``step()`` — nothing wall-clock-dependent is
traced (the compiled steps see only token/position values).

NB p99 over a few dozen requests is a tail *estimate*; the record
carries ``completed`` so readers can judge the sample size.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# --dryrun must win before jax initializes (same recipe as tests/conftest.py)
if "--dryrun" in sys.argv:
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def log(msg):
    print(f"[bench_serving] {msg}", file=sys.stderr, flush=True)


def emit(rec, rung="serving"):
    print(json.dumps(rec), flush=True)
    from deepspeed_tpu.telemetry.regression import tool_history_emit

    # standalone runs feed the persistent bench history too (no-op when
    # the bench.py driver parent is the history writer)
    tool_history_emit(rec, rung=rung,
                      base_dir=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_workload(n, prompt_lo, prompt_hi, max_new, seed, vocab):
    """Seeded request set: ragged prompts + per-request generation
    budgets (arrival times are drawn per load in run_load)."""
    rng = np.random.default_rng(seed)
    return [
        {
            "prompt": rng.integers(1, vocab, int(rng.integers(prompt_lo, prompt_hi + 1)),
                                   dtype=np.int32),
            "max_new": int(max_new),
        }
        for _ in range(n)
    ]


def warm(srv, workload):
    """Compile both serving executables before the measured window (a
    fresh ServingEngine's first chunk/decode otherwise charges the jit
    trace to the first request's latency).  Priority 0: the warm-up
    must admit even when --overload arms the shedder."""
    w = workload[0]
    srv.submit(w["prompt"], max_new_tokens=min(2, w["max_new"]), priority=0)
    srv.drain(max_steps=10_000)
    srv.timeline.reset_window()
    return srv


def run_closed_loop(make_serving, workload):
    """Everything submitted at t=0 → drain: the capacity measurement the
    offered loads are scaled from."""
    from deepspeed_tpu.serving import ServingQueueFull

    srv = warm(make_serving(), workload)
    t0 = time.monotonic()
    for w in workload:
        while True:
            try:
                srv.submit(w["prompt"], max_new_tokens=w["max_new"])
                break
            except ServingQueueFull:  # bounded queue: drain a step, retry
                srv.step()
    res = srv.drain(max_steps=100_000)
    dt = time.monotonic() - t0
    toks = sum(len(r.generated) for r in res.values())
    return toks / max(dt, 1e-9), len(res) / max(dt, 1e-9), dt


def run_load(make_serving, workload, offered_rps, seed):
    """Open-loop seeded Poisson run at ``offered_rps`` requests/s."""
    from deepspeed_tpu.serving import ServingQueueFull

    srv = warm(make_serving(), workload)
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, size=len(workload)))
    t0 = time.monotonic()
    pending = list(zip(arrivals, workload))
    ids = {}  # request_id -> scheduled arrival offset
    finished = {}
    shed_retry = []  # retry_after hints carried by shed/queue-full rejections
    while pending or srv.scheduler.has_work():
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            arr, w = pending.pop(0)
            try:
                rid = srv.submit(w["prompt"], max_new_tokens=w["max_new"])
                ids[rid] = arr
            except ServingQueueFull as e:
                # shed load under overload; scheduler counts the rejection
                if e.retry_after is not None:
                    shed_retry.append(e.retry_after)
        if srv.scheduler.has_work():
            srv.step()
        elif pending:
            # idle until the next arrival (host sleep, nothing traced)
            time.sleep(min(0.005, max(0.0, pending[0][0] - now)))
        finished.update(srv.pop_results())
    makespan = time.monotonic() - t0
    ttft, ttft_submit, tpot, toks = [], [], [], 0
    for rid, arr in ids.items():
        r = finished.get(rid)
        if r is None or r.first_token_time is None:
            continue
        toks += len(r.generated)
        ttft.append((r.first_token_time - t0 - arr) * 1e3)
        # submit-anchored TTFT: the same timestamps the telemetry
        # plane's per-request spans carry, so a trace.json reconstructs
        # these two percentiles exactly (docs/telemetry.md; the
        # arrival-anchored ttft_* above additionally charges the
        # bench's submission-poll delay)
        ttft_submit.append((r.first_token_time - r.submit_time) * 1e3)
        if len(r.generated) > 1 and r.finish_time is not None:
            tpot.append(
                (r.finish_time - r.first_token_time) * 1e3 / (len(r.generated) - 1)
            )
    pct = lambda a, q: round(float(np.percentile(a, q)), 2) if a else None
    stats = srv.stats()
    tel = srv.telemetry_summary()
    return {
        "tokens_per_s": round(toks / max(makespan, 1e-9), 1),
        "ttft_p50_ms": pct(ttft, 50),
        "ttft_p99_ms": pct(ttft, 99),
        "ttft_submit_p50_ms": pct(ttft_submit, 50),
        "ttft_submit_p99_ms": pct(ttft_submit, 99),
        "tpot_p50_ms": pct(tpot, 50),
        "tpot_p99_ms": pct(tpot, 99),
        "completed": len(ttft),
        "rejected": stats["rejected"],
        "expired": stats["expired"],
        # overload-resilience fields (docs/serving.md §Resilience):
        # shed_rate over OFFERED requests; the ttft_* percentiles above
        # are admitted-only, which is exactly the shedder's SLO claim
        "shed": stats["shed"],
        "shed_rate": round(stats["rejected"] / max(len(workload), 1), 3),
        "retry_after_p50_s": pct(shed_retry, 50),
        "degrade_engagements": stats["degrade_engagements"],
        "degrade_level_final": stats["degrade_level"],
        "offered_rps": round(offered_rps, 3),
        "prefill_ms": stats["prefill_ms"],
        "decode_ms": stats["decode_ms"],
        "sched_ms": stats["sched_ms"],
        "queue_depth": stats["queue_depth"],
        "decode_compiles": stats["decode_compiles"],
        "mfu": tel["mfu"],
        "hbm_bytes_per_step": tel["hbm_bytes_per_step"],
        "telemetry": tel["telemetry"],
        **({"ds_san": True} if srv._sanitizer is not None else {}),
    }


def run_fleet_load(router, reps, workload, offered_rps, seed, kill_at_frac=None):
    """Open-loop seeded Poisson run through the FleetRouter; with
    ``kill_at_frac`` the busiest replica is killed once that fraction of
    the arrival schedule has elapsed (the failover measurement)."""
    from deepspeed_tpu.serving.fleet import FleetOverloaded

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, size=len(workload)))
    kill_at = (
        float(arrivals[max(int(len(arrivals) * kill_at_frac) - 1, 0)])
        if kill_at_frac is not None else None
    )
    t0 = time.monotonic()
    pending = list(zip(arrivals, workload))
    handles = {}  # handle_id -> scheduled arrival offset
    finished = {}
    rejected = 0
    while pending or router.has_work():
        now = time.monotonic() - t0
        if kill_at is not None and now >= kill_at:
            victim = max((r for r in reps if r.alive()),
                         key=lambda r: r.queue_depth())
            victim.kill("bench chaos: kill mid-run")
            kill_at = None
        while pending and pending[0][0] <= now:
            arr, w = pending.pop(0)
            try:
                hid = router.submit(w["prompt"], max_new_tokens=w["max_new"])
                handles[hid] = arr
            except FleetOverloaded:
                rejected += 1
        if router.has_work():
            router.step()
        elif pending:
            time.sleep(min(0.005, max(0.0, pending[0][0] - now)))
        finished.update(router.pop_results())
    makespan = time.monotonic() - t0
    # quiesce: a background restart may still be rebuilding after the
    # last result lands — step it to completion so the record carries
    # the restart count and the process doesn't exit mid-compile
    sup = getattr(router, "_supervisor", None)
    if sup is not None:
        while sup.pending():
            router.step()
        router.step()  # absorb a completion that landed after the last poll
    finished.update(router.pop_results())
    ttft, toks = [], 0
    for hid, arr in handles.items():
        r = finished.get(hid)
        if r is None or r.first_token_time is None:
            continue
        toks += len(r.generated)
        # submit-anchored admitted TTFT — a refired/replayed request's
        # clock restarts with its re-admission, which is exactly the
        # replica-local latency the failover SLO is about
        ttft.append((r.first_token_time - r.submit_time) * 1e3)
    pct = lambda a, q: round(float(np.percentile(a, q)), 2) if a else None
    st = router.stats()
    return {
        "tokens_per_s": round(toks / max(makespan, 1e-9), 1),
        "ttft_submit_p50_ms": pct(ttft, 50),
        "ttft_submit_p99_ms": pct(ttft, 99),
        "completed": len(ttft),
        "offered": len(workload),
        "availability": round(len(ttft) / max(len(workload), 1), 3),
        "rejected": rejected,
        "deaths": st["deaths"],
        "restarts": st["restarts"],
        "failovers": st["failovers"],
        "refired": st["refired"],
        "offered_rps": round(offered_rps, 3),
    }


def run_fleet_bench(engine, args, slots, chunk, max_len, max_new, workload, model):
    """The ``fleet`` bench rung: a 3-replica FleetRouter under seeded
    Poisson load, measured twice with the SAME arrival schedule —
    steady-state, then with one replica killed mid-run and supervised
    back to life.  The PR 11 perf sentinel gates the emitted record;
    its headline ratio is failover-p99 TTFT over steady-state p99 (the
    fleet proof bound: <= 2x)."""
    import tempfile

    from deepspeed_tpu.serving import ServingEngine
    from deepspeed_tpu.serving.fleet import (
        FleetRouter,
        LocalReplica,
        ReplicaSupervisor,
    )

    n_replicas = 3
    # 4x the serving workload: p99 over a dozen samples is just the max
    # sample, which makes the failover ratio a coin flip on whichever
    # request happened to straddle the kill
    workload = workload * 4

    with tempfile.TemporaryDirectory(prefix="bench_fleet_") as root:
        def build_fleet(tag):
            def factory(name):
                d = os.path.join(root, tag, name, "journal")
                return lambda: ServingEngine(
                    engine, num_slots=slots, prefill_chunk=chunk,
                    max_len=max_len, max_queue=args.max_queue,
                    max_new_tokens=max_new, journal_dir=d,
                )
            # the warm hook compiles both executables per engine build —
            # INCLUDING supervised restarts, so the rebuilt replica's jit
            # trace never lands on the replayed requests' TTFT
            reps = [
                LocalReplica(f"r{i}", factory(f"r{i}"),
                             warm=lambda e: warm(e, workload))
                for i in range(n_replicas)
            ]
            # background=True: the supervised restart (rebuild + warm +
            # replay) runs on a thread while the survivors keep serving —
            # a synchronous restart would block the routing loop for the
            # whole rebuild and charge it to every in-flight TTFT
            router = FleetRouter(
                reps,
                supervisor=ReplicaSupervisor(max_restarts=n_replicas,
                                             background=True),
                seed=args.seed,
            )
            return router, reps

        # capacity anchor: one replica's closed-loop service rate
        def make_one():
            return ServingEngine(engine, num_slots=slots, prefill_chunk=chunk,
                                 max_len=max_len, max_queue=args.max_queue,
                                 max_new_tokens=max_new)

        _, req_s, _ = run_closed_loop(make_one, workload)
        # 1.5x one replica's capacity (50% fleet utilization), but the
        # arrival schedule must SPAN the kill + supervised restart —
        # a rate that drains the whole workload in a fraction of a
        # second turns the failover run into a burst test where queue
        # depth, not failover, sets the tail
        offered = max(min(req_s * 1.5, len(workload) / 5.0), 1e-3)
        log(f"[fleet] single-replica capacity {req_s:.2f} req/s; "
            f"offering {offered:.2f} req/s to {n_replicas} replicas "
            f"over ~{len(workload) / offered:.1f}s")

        router, reps = build_fleet("steady")
        steady = run_fleet_load(router, reps, workload, offered, args.seed)
        log(f"[fleet] steady-state: {steady['tokens_per_s']} tok/s, "
            f"admitted p99 {steady['ttft_submit_p99_ms']} ms")

        router, reps = build_fleet("chaos")
        chaos = run_fleet_load(router, reps, workload, offered, args.seed,
                               kill_at_frac=0.4)
        if chaos["deaths"] < 1:
            log("[fleet] WARNING: the kill never fired (run too short?)")

    ratio = None
    if steady["ttft_submit_p99_ms"] and chaos["ttft_submit_p99_ms"]:
        ratio = round(
            chaos["ttft_submit_p99_ms"] / steady["ttft_submit_p99_ms"], 3
        )
    rec = {
        "metric": f"serving_fleet_{model.replace('-', '_')}_3rep_kill1",
        "value": chaos.pop("tokens_per_s"),
        "unit": "tokens/s",
        "replicas": n_replicas,
        "num_slots": slots,
        "prefill_chunk": chunk,
        "max_len": max_len,
        "requests": len(workload),
        "failover_over_steady_p99": ratio,
        "steady_tokens_per_s": steady["tokens_per_s"],
        "steady_ttft_submit_p99_ms": steady["ttft_submit_p99_ms"],
        **chaos,
    }
    emit(rec, rung="fleet")
    log(f"[fleet] kill-1-of-3: {rec['value']} tok/s "
        f"(steady {rec['steady_tokens_per_s']}), admitted p99 "
        f"{rec['ttft_submit_p99_ms']} ms = {ratio}x steady, "
        f"availability {rec['availability']:.1%}, deaths {rec['deaths']}, "
        f"restarts {rec['restarts']}")


def run_elastic_load(router, auto, workload, offered_rps, seed,
                     scale_down_at_frac=None):
    """Open-loop seeded Poisson run through an AUTOSCALED fleet: the
    :class:`FleetAutoscaler` ticks on the routing loop (its contract);
    with ``scale_down_at_frac`` a forced scale-down (drain + live KV
    migration) is requested once that fraction of the arrival schedule
    has elapsed.  ``auto=None`` runs the same loop without elasticity
    (the steady-state baseline)."""
    from deepspeed_tpu.serving.fleet import FleetOverloaded

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, size=len(workload)))
    down_at = (
        float(arrivals[max(int(len(arrivals) * scale_down_at_frac) - 1, 0)])
        if scale_down_at_frac is not None else None
    )
    t0 = time.monotonic()
    pending = list(zip(arrivals, workload))
    handles = {}  # handle_id -> scheduled arrival offset
    finished = {}
    rejected = 0
    peak_replicas = len(router._order)
    scale_down_requested = False
    while (pending or router.has_work()
           or (auto is not None and auto.stats()["phase"] != "idle")):
        now = time.monotonic() - t0
        if down_at is not None and now >= down_at:
            scale_down_requested = auto.request_scale_down()
            down_at = None
        while pending and pending[0][0] <= now:
            arr, w = pending.pop(0)
            try:
                hid = router.submit(w["prompt"], max_new_tokens=w["max_new"])
                handles[hid] = arr
            except FleetOverloaded:
                rejected += 1  # shed: the fleet is saturated end to end
        if auto is not None:
            auto.tick()
            peak_replicas = max(peak_replicas, len(router._order))
        if router.has_work():
            router.step()
        elif pending:
            time.sleep(min(0.005, max(0.0, pending[0][0] - now)))
        finished.update(router.pop_results())
    makespan = time.monotonic() - t0
    finished.update(router.pop_results())
    ttft, toks = [], 0
    for hid, arr in handles.items():
        r = finished.get(hid)
        if r is None or r.first_token_time is None:
            continue
        toks += len(r.generated)
        # submit-anchored admitted-only TTFT: the autoscaler's SLO claim
        # is about what the fleet ADMITTED while shedding the rest
        ttft.append((r.first_token_time - r.submit_time) * 1e3)
    pct = lambda a, q: round(float(np.percentile(a, q)), 2) if a else None
    return {
        "tokens_per_s": round(toks / max(makespan, 1e-9), 1),
        "ttft_submit_p50_ms": pct(ttft, 50),
        "ttft_submit_p99_ms": pct(ttft, 99),
        "completed": len(ttft),
        "offered": len(workload),
        "admitted": len(handles),
        "shed_rate": round(rejected / max(len(workload), 1), 3),
        "offered_rps": round(offered_rps, 3),
        "peak_replicas": peak_replicas,
        "scale_down_requested": scale_down_requested,
        "makespan_s": round(makespan, 2),
    }


def run_elastic_bench(engine, args, slots, chunk, max_len, max_new,
                      workload, model):
    """The ``elastic`` bench rung (docs/serving.md §Elastic fleet): an
    autoscaled fleet under ~10x one replica's offered load.  One paged
    replica + a FleetAutoscaler (warm pool pre-compiles off the routing
    thread) absorb a seeded Poisson surge; mid-surge a FORCED scale-down
    drains a victim and live-migrates its KV.  The record carries
    aggregate tokens/s, admitted-p99 TTFT (and its ratio over a
    single-replica steady state), shed rate, and the scale-up /
    scale-down reaction times."""
    import tempfile

    from deepspeed_tpu.serving import ServingEngine
    from deepspeed_tpu.serving.fleet import (
        FleetAutoscaler,
        FleetRouter,
        LocalReplica,
    )

    base = workload

    with tempfile.TemporaryDirectory(prefix="bench_elastic_") as root:
        def mk_factory(tag):
            def factory(name):
                d = os.path.join(root, tag, name, "journal")

                def build():
                    return ServingEngine(
                        engine, num_slots=slots, prefill_chunk=chunk,
                        max_len=max_len, max_queue=args.max_queue,
                        max_new_tokens=max_new, journal_dir=d,
                        slo_ttft_ms=args.slo_ttft_ms,
                        kvcache={"enabled": True, "page_len": chunk},
                    )
                return LocalReplica(name, build,
                                    warm=lambda e: warm(e, base))
            return factory

        # capacity anchor: one replica's closed-loop service rate sets
        # the offered-load scale (the shedder never engages closed-loop)
        def make_one():
            return ServingEngine(
                engine, num_slots=slots, prefill_chunk=chunk,
                max_len=max_len, max_queue=args.max_queue,
                max_new_tokens=max_new,
                kvcache={"enabled": True, "page_len": chunk},
            )

        _, req_s, _ = run_closed_loop(make_one, base)

        # steady-state baseline: ONE replica comfortably under capacity
        # — the denominator of the elastic p99 ratio
        steady_factory = mk_factory("steady")
        router = FleetRouter([steady_factory("r0")], seed=args.seed)
        steady = run_elastic_load(router, None, base * 2,
                                  max(req_s * 0.6, 1e-3), args.seed)
        log(f"[elastic] single-replica capacity {req_s:.2f} req/s; steady "
            f"admitted p99 {steady['ttft_submit_p99_ms']} ms")

        # the surge: ~10x one replica's capacity, sized so the arrival
        # window spans scale-up + mid-surge forced scale-down
        offered = max(req_s * 10.0, 1e-3)
        n_need = max(int(offered * 6.0) + 1, len(base))
        surge = (base * (n_need // len(base) + 1))[:n_need]
        elastic_factory = mk_factory("elastic")
        router = FleetRouter([elastic_factory("r0")], seed=args.seed)
        auto = FleetAutoscaler(
            router, elastic_factory,
            config={
                "enabled": True, "min_replicas": 1, "max_replicas": 3,
                "scale_up_queue_depth": max(slots, 4),
                "scale_up_ttft_seconds": args.slo_ttft_ms / 1e3,
                "scale_down_queue_depth": 1,
                "engage_ticks": 3,
                "disengage_ticks": 10 ** 6,  # scale-down is forced mid-run
                "scale_up_cooldown_seconds": 1.0,
                "scale_down_cooldown_seconds": 0.0,
                "warm_pool_size": 1,
                "migration_deadline_seconds": 120.0,
                "migration_retries": 2,
            },
            handoff_root=root,
        )
        try:
            log(f"[elastic] offering {offered:.2f} req/s "
                f"(~{offered / max(req_s, 1e-9):.1f}x capacity, "
                f"{len(surge)} requests over ~{len(surge) / offered:.1f}s)")
            elastic = run_elastic_load(router, auto, surge, offered,
                                       args.seed, scale_down_at_frac=0.55)
            st = auto.stats()
        finally:
            auto.stop()

    ratio = None
    if steady["ttft_submit_p99_ms"] and elastic["ttft_submit_p99_ms"]:
        ratio = round(
            elastic["ttft_submit_p99_ms"] / steady["ttft_submit_p99_ms"], 3
        )
    rec = {
        "metric": f"serving_elastic_{model.replace('-', '_')}_10x_autoscale",
        "value": elastic.pop("tokens_per_s"),
        "unit": "tokens/s",
        "offered_x_capacity": round(offered / max(req_s, 1e-9), 2),
        "num_slots": slots,
        "prefill_chunk": chunk,
        "max_len": max_len,
        "slo_ttft_ms": args.slo_ttft_ms,
        "elastic_over_steady_p99": ratio,
        "steady_tokens_per_s": steady["tokens_per_s"],
        "steady_ttft_submit_p99_ms": steady["ttft_submit_p99_ms"],
        "scale_ups": st["scale_ups"],
        "scale_downs": st["scale_downs"],
        "scale_downs_aborted": st["scale_downs_aborted"],
        "scale_up_reaction_s": (
            round(st["last_scale_up_reaction_s"], 3)
            if st["last_scale_up_reaction_s"] is not None else None),
        "scale_down_reaction_s": (
            round(st["last_scale_down_reaction_s"], 3)
            if st["last_scale_down_reaction_s"] is not None else None),
        "migrations_completed": st["migrations_completed"],
        "migrations_failed": st["migrations_failed"],
        "sessions_migrated": st["sessions_migrated"],
        "warm_pool_built": st["warm_pool"]["built"],
        **elastic,
    }
    emit(rec, rung="elastic")
    log(f"[elastic] {rec['offered_x_capacity']}x offered: {rec['value']} "
        f"tok/s aggregate, admitted p99 {rec['ttft_submit_p99_ms']} ms "
        f"= {ratio}x steady, shed {rec['shed_rate']:.1%}, "
        f"scale-up x{rec['scale_ups']} ({rec['scale_up_reaction_s']}s), "
        f"scale-down x{rec['scale_downs']} "
        f"({rec['scale_down_reaction_s']}s), "
        f"{rec['sessions_migrated']} session(s) migrated")


def run_tenant_load(make_serving, schedule):
    """Open-loop run over a pre-merged ``[(arrival_s, item), ...]``
    schedule where every item carries a ``tenant``; returns per-tenant
    admitted TTFT percentiles plus throttle counts (a throttled submit
    raises ``TenantThrottled`` — a ``ServingQueueFull`` subclass — and
    counts as that tenant's rejection, exactly the front-door's 429)."""
    from deepspeed_tpu.serving import ServingQueueFull

    srv = warm(make_serving(), [w for _, w in schedule])
    t0 = time.monotonic()
    pending = list(schedule)
    ids = {}  # rid -> (tenant, arrival offset)
    finished = {}
    rejected = {}  # tenant -> throttled/queue-full submit count
    while pending or srv.scheduler.has_work():
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            arr, w = pending.pop(0)
            try:
                rid = srv.submit(w["prompt"], max_new_tokens=w["max_new"],
                                 tenant=w["tenant"])
                ids[rid] = (w["tenant"], arr)
            except ServingQueueFull:
                rejected[w["tenant"]] = rejected.get(w["tenant"], 0) + 1
        if srv.scheduler.has_work():
            srv.step()
        elif pending:
            time.sleep(min(0.005, max(0.0, pending[0][0] - now)))
        finished.update(srv.pop_results())
    makespan = time.monotonic() - t0
    per, per_steps, toks = {}, {}, 0
    for rid, (tn, arr) in ids.items():
        r = finished.get(rid)
        if r is None or r.first_token_time is None:
            continue
        toks += len(r.generated)
        per.setdefault(tn, []).append(
            (r.first_token_time - r.submit_time) * 1e3)
        # submit-to-first-token in SCHEDULER STEPS: the virtual-time
        # view of the same wait (queue + chunked prefill), immune to
        # the host descheduling that makes wall-clock ms ungateable
        # on shared runners — a stalled host stops the step clock too
        per_steps.setdefault(tn, []).append(
            r.first_token_step - r.submit_step)
    pct = lambda a, q: round(float(np.percentile(a, q)), 2) if a else None
    return {
        "tokens_per_s": round(toks / max(makespan, 1e-9), 1),
        "tenants": {
            tn: {
                "completed": len(per.get(tn, [])),
                "rejected": rejected.get(tn, 0),
                "ttft_submit_p50_ms": pct(per.get(tn, []), 50),
                "ttft_submit_p99_ms": pct(per.get(tn, []), 99),
                "ttft_steps_p50": pct(per_steps.get(tn, []), 50),
                "ttft_steps_p99": pct(per_steps.get(tn, []), 99),
            }
            for tn in sorted(set(per) | set(rejected))
        },
    }


def run_tenant_bench(engine, args, slots, chunk, max_len, max_new, model):
    """The ``tenants`` bench rung (docs/serving.md §Front-door): the
    multi-tenant isolation proof.  A QUIET tenant runs the same seeded
    Poisson stream twice — once alone, once next to a NOISY tenant
    offered 10x its token-bucket quota.  The bucket + weighted-fair
    queue must absorb the noisy tenant (throttled at admission, fair-
    queued behind quiet's requests when admitted), so the quiet
    tenant's admitted median TTFT in the mixed run — measured in
    scheduler steps, the engine's virtual clock — IS the gated metric:
    if isolation breaks, the quiet tenant queues for more steps, the
    number inflates past the noise band and the perf sentinel goes
    red."""
    from deepspeed_tpu.serving import ServingEngine

    log("=== mixed-tenant isolation bench ===")
    rng = np.random.default_rng(args.seed)
    n_req = args.requests or 16
    lo, hi = 4, min(48, max_len // 2)
    base = build_workload(n_req, lo, hi, max_new, args.seed,
                          engine.model_config.vocab_size)
    # the bucket charges prompt + max_new at admission — quota math is
    # in TOKENS/s, so size it off the mean request cost
    cost = float(np.mean([len(w["prompt"]) + w["max_new"] for w in base]))

    # raw capacity (no tenants armed) sizes both offered rates
    def make_plain():
        return ServingEngine(engine, num_slots=slots, prefill_chunk=chunk,
                             max_len=max_len, max_queue=args.max_queue)

    toks_s, req_s, _ = run_closed_loop(make_plain, base)
    quiet_rps = max(req_s * 0.4, 1e-3)
    noisy_quota_rps = max(req_s * 0.3, 1e-3)  # the bucket's sustained rate
    noisy_offered_rps = noisy_quota_rps * 10.0  # 10x its quota
    log(f"[tenants] capacity {req_s:.2f} req/s; quiet offered "
        f"{quiet_rps:.2f} req/s, noisy offered {noisy_offered_rps:.2f} "
        f"req/s against a {noisy_quota_rps:.2f} req/s quota")

    tenants_cfg = {
        "enabled": True,
        "overrides": {
            # quiet: unlimited bucket, gold SLO (maps to priority 0)
            "quiet": {"slo_class": "gold"},
            # noisy: bucket sized to ~30% of capacity in token terms
            "noisy": {
                "refill_tokens_per_second": noisy_quota_rps * cost,
                "burst_tokens": max(2.0 * cost, 1.0),
                "slo_class": "bronze",
            },
        },
    }

    def make_tenanted():
        return ServingEngine(engine, num_slots=slots, prefill_chunk=chunk,
                             max_len=max_len, max_queue=args.max_queue,
                             tenants=tenants_cfg)

    # the quiet stream: IDENTICAL arrivals in both phases (same seed)
    quiet_items = [dict(w, tenant="quiet") for w in base]
    quiet_arr = np.cumsum(
        np.random.default_rng(args.seed + 1).exponential(
            1.0 / quiet_rps, size=len(quiet_items)))
    sched_quiet = sorted(zip(quiet_arr.tolist(), quiet_items))

    # latency noise on a shared host is one-sided (descheduling only
    # ADDS time), so each phase runs ``repeats`` times and the gated
    # number is the BEST step-count p50 — a real isolation regression
    # is workload behaviour and inflates every repeat, a jitter
    # outlier only one
    repeats = 5

    def best(runs_):
        return min(runs_, key=lambda r: (
            r["tenants"]["quiet"]["ttft_steps_p50"]
            if r["tenants"]["quiet"]["ttft_steps_p50"] is not None
            else float("inf")))

    solo_runs = [run_tenant_load(make_tenanted, sched_quiet)
                 for _ in range(repeats)]
    solo = best(solo_runs)
    q_solo = solo["tenants"]["quiet"]
    log(f"[tenants] quiet solo: admitted p50 "
        f"{q_solo['ttft_steps_p50']} steps / "
        f"{q_solo['ttft_submit_p50_ms']} ms best-of-{repeats} (p99 "
        f"{q_solo['ttft_submit_p99_ms']} ms, "
        f"{q_solo['completed']}/{len(quiet_items)} completed)")

    # the noisy stream spans the quiet window at 10x quota
    window_s = float(quiet_arr[-1])
    n_noisy = max(int(noisy_offered_rps * window_s) + 1, 4)
    noisy_base = (base * (n_noisy // len(base) + 1))[:n_noisy]
    noisy_items = [dict(w, tenant="noisy") for w in noisy_base]
    noisy_arr = np.cumsum(rng.exponential(
        1.0 / noisy_offered_rps, size=len(noisy_items)))
    merged = sorted(
        list(zip(quiet_arr.tolist(), quiet_items))
        + list(zip(noisy_arr.tolist(), noisy_items)),
        key=lambda p: p[0])

    mixed_runs = [run_tenant_load(make_tenanted, merged)
                  for _ in range(repeats)]
    mixed = best(mixed_runs)
    q_mix = mixed["tenants"]["quiet"]
    n_mix = mixed["tenants"].get(
        "noisy", {"completed": 0, "rejected": 0,
                  "ttft_submit_p99_ms": None})
    ratio = None
    if q_solo["ttft_steps_p50"] and q_mix["ttft_steps_p50"]:
        ratio = round(
            q_mix["ttft_steps_p50"] / q_solo["ttft_steps_p50"], 3)
    throttle_rate = round(
        n_mix["rejected"] / max(len(noisy_items), 1), 3)
    rec = {
        # "ttft"/"p50" tokens -> lower-is-better for the perf
        # sentinel; a DS_BENCH_INJECT 'tenants:3.0' triples it -> RED
        # (CI check).  Gated on the quiet tenant's MEDIAN submit-to-
        # first-token measured in SCHEDULER STEPS (virtual time), best
        # of ``repeats`` identical mixed phases: a starved tenant
        # queues for more steps in every repeat, while wall-clock ms
        # at single-digit magnitudes is dominated by shared-runner
        # descheduling (the ms percentiles ride along as context)
        "metric": f"serving_tenants_{model.replace('-', '_')}"
                  "_quiet_ttft_p50_steps_under_10x_noisy",
        "value": q_mix["ttft_steps_p50"],
        "unit": "steps",
        "repeats": repeats,
        "quiet_steps_p50_runs": [
            r["tenants"]["quiet"]["ttft_steps_p50"]
            for r in mixed_runs],
        "quiet_steps_p99": q_mix["ttft_steps_p99"],
        "quiet_solo_steps_p50": q_solo["ttft_steps_p50"],
        "quiet_p50_ms": q_mix["ttft_submit_p50_ms"],
        "quiet_p99_ms": q_mix["ttft_submit_p99_ms"],
        "quiet_solo_p50_ms": q_solo["ttft_submit_p50_ms"],
        "quiet_solo_p99_ms": q_solo["ttft_submit_p99_ms"],
        "quiet_mixed_over_solo_p50_steps": ratio,
        "quiet_completed": q_mix["completed"],
        "quiet_offered": len(quiet_items),
        "quiet_rejected": q_mix["rejected"],
        "noisy_offered": len(noisy_items),
        "noisy_completed": n_mix["completed"],
        "noisy_throttled": n_mix["rejected"],
        "noisy_throttle_rate": throttle_rate,
        "noisy_p99_ms": n_mix["ttft_submit_p99_ms"],
        "noisy_offered_x_quota": 10.0,
        "capacity_req_s": round(req_s, 2),
        "tokens_per_s": mixed["tokens_per_s"],
        "num_slots": slots,
        "prefill_chunk": chunk,
        "max_len": max_len,
    }
    emit(rec, rung="tenants")
    log(f"[tenants] mixed: quiet admitted p50 {rec['value']} steps "
        f"best-of-{repeats} {rec['quiet_steps_p50_runs']} "
        f"= {ratio}x solo ({rec['quiet_p50_ms']} ms, p99 "
        f"{rec['quiet_p99_ms']} ms); "
        f"noisy throttled {throttle_rate:.1%} "
        f"({n_mix['rejected']}/{len(noisy_items)}), quiet rejected "
        f"{q_mix['rejected']}")


def run_kvcache_bench(engine, args, slots, chunk, max_len, max_new, model):
    """The ``kvcache`` bench rung (docs/serving.md §Paged KV & prefix
    caching): an 80%-shared system-prompt batch plus 3-turn chat
    sessions, run twice with the SAME schedule — paged KV on vs off.
    The record proves the three acceptance claims at once: greedy
    outputs bit-identical, prefill FLOPs (chunk dispatches) reduced
    >= 2x, and TTFT p50/p99 measurably lower with the cache on."""
    from deepspeed_tpu.serving import ServingEngine

    rng = np.random.default_rng(args.seed)
    vocab = engine.model_config.vocab_size
    sys_len = max_len // 2  # the shared system prompt
    sys_prompt = rng.integers(1, vocab, sys_len, dtype=np.int32)
    n_req = args.requests or 12
    budget = min(max_new, 6)
    tail = lambda lo, hi: rng.integers(
        1, vocab, int(rng.integers(lo, hi + 1)), dtype=np.int32)
    # 80% of the batch shares the system prompt; the rest is cold
    batch = [
        np.concatenate([sys_prompt, tail(chunk // 4, chunk)])
        if i % 5 != 4 else tail(sys_len // 2, sys_len)
        for i in range(n_req)
    ]
    n_sess, n_turns = 3, 3
    sess_tails = [[tail(chunk // 4, chunk // 2) for _ in range(n_turns)]
                  for _ in range(n_sess)]

    def run(kvcache_on):
        kw = {"kvcache": {"enabled": True, "page_len": chunk}} if kvcache_on else {}
        srv = ServingEngine(engine, num_slots=slots, prefill_chunk=chunk,
                            max_len=max_len, max_queue=args.max_queue,
                            max_new_tokens=budget, **kw)
        warm(srv, [{"prompt": batch[0][: chunk // 2], "max_new": 2}])
        outputs, ttfts, chunks = [], [], 0
        t0 = time.monotonic()

        def go(prompts, **skw):
            nonlocal chunks
            rids = [srv.submit(p, max_new_tokens=budget, **dict(skw, **e))
                    for p, e in prompts]
            chunks += sum(-(-len(p) // chunk) for p, _ in prompts)
            res = srv.drain(max_steps=100_000)
            for rid in rids:
                r = res[rid]
                outputs.append(np.asarray(r.tokens()))
                ttfts.append((r.first_token_time - r.submit_time) * 1e3)
            return [np.asarray(res[rid].tokens()) for rid in rids]

        # seed the shared prefix (prefix warming: one full prefill both
        # runs pay; every later shared prompt can then hit)
        go([(sys_prompt, {})])
        # phase A: the shared-prefix batch, all offered at once
        go([(p, {}) for p in batch])
        # phase B: 3-turn sessions (turn n+1 extends turn n's output)
        hist = [np.concatenate([sys_prompt, sess_tails[s][0]])
                for s in range(n_sess)]
        for turn in range(n_turns):
            outs = go([(hist[s], {"session_id": f"sess-{s}"})
                       for s in range(n_sess)])
            if turn + 1 < n_turns:
                hist = [np.concatenate([outs[s], sess_tails[s][turn + 1]])
                        for s in range(n_sess)]
        makespan = time.monotonic() - t0
        toks = sum(len(o) for o in outputs)
        kv = srv.stats().get("kvcache") if kvcache_on else None
        return outputs, ttfts, chunks, makespan, toks, kv

    out_off, ttft_off, chunks_off, span_off, toks_off, _ = run(False)
    out_on, ttft_on, chunks_on_sched, span_on, toks_on, kv = run(True)
    bit_identical = len(out_on) == len(out_off) and all(
        np.array_equal(a, b) for a, b in zip(out_on, out_off)
    )
    # prefix hits are chunk-aligned, so saved chunks are exact
    chunks_on = chunks_on_sched - kv["tokens_saved"] // chunk
    reduction = round(chunks_off / max(chunks_on, 1), 3)
    pct = lambda a, q: round(float(np.percentile(a, q)), 2) if a else None
    rec = {
        "metric": f"serving_kvcache_{model.replace('-', '_')}_prefix_session",
        "value": reduction,
        "unit": "x_prefill_flops",
        "bit_identical": bit_identical,
        "hit_rate": kv["hit_rate"],
        "tokens_saved": kv["tokens_saved"],
        "prefill_chunks_off": chunks_off,
        "prefill_chunks_on": chunks_on,
        "ttft_p50_ms_on": pct(ttft_on, 50),
        "ttft_p99_ms_on": pct(ttft_on, 99),
        "ttft_p50_ms_off": pct(ttft_off, 50),
        "ttft_p99_ms_off": pct(ttft_off, 99),
        "tokens_per_s_on": round(toks_on / max(span_on, 1e-9), 1),
        "tokens_per_s_off": round(toks_off / max(span_off, 1e-9), 1),
        "cow_copies": kv["cow_copies"],
        "session_rebinds": kv["session_rebinds"],
        "evictions": kv["evictions"],
        "page_len": kv["page_len"],
        "requests": len(out_on),
        "num_slots": slots,
        "prefill_chunk": chunk,
        "max_len": max_len,
    }
    emit(rec, rung="kvcache")
    log(f"[kvcache] prefill FLOPs {reduction}x lower "
        f"({chunks_off} -> {chunks_on} chunks), hit rate "
        f"{kv['hit_rate']:.0%}, ttft p50 {rec['ttft_p50_ms_on']} ms vs "
        f"{rec['ttft_p50_ms_off']} ms off, bit_identical={bit_identical}")


def run_kvtiers_bench(engine, args, slots, chunk, max_len, max_new, model):
    """The ``kvtiers`` rung (docs/serving.md §KV tiering): a long-context
    session fleet whose parked working set is ~4x the device page pool,
    run three ways with the SAME prompt schedule —

    * all-HBM reference (paged KV, pool sized to hold everything);
    * tiering armed but T0-resident (same big pool + tiers: measures the
      tier manager's overhead when nothing needs to move);
    * tiering armed at ~4x oversubscription (tiny T0, host + disk tiers
      absorb the rest; every turn revisits sessions demoted since).

    Gates: greedy outputs bit-identical to the all-HBM run, zero
    ServingQueueFull at 4x, T0-resident tokens/s within 10% of all-HBM
    (recorded as ``tok_ratio_resident``); ``swap_hidden_ratio`` records
    what fraction of device<->host/disk migration time hid beneath
    serving steps (soft gate >= 0.8)."""
    import shutil
    import tempfile

    from deepspeed_tpu.serving import ServingEngine

    rng = np.random.default_rng(args.seed)
    vocab = engine.model_config.vocab_size
    page_len = chunk
    n_sess, n_turns = 8, 3
    tail_len = max(4, page_len // 2)
    budget = max(2, min(max_new, page_len // 4))
    pages_for = lambda toks: -(-max(toks, 1) // page_len)
    # the working set is what the sessions park by the end; size T0 to a
    # quarter of it (but never below one max request's upfront claim)
    parked_toks = n_turns * (tail_len + budget) - 1
    ws_pages = n_sess * pages_for(parked_toks)
    per_req = pages_for(n_turns * (tail_len + budget)) + 1  # +1 COW page
    t0_usable = max(-(-ws_pages // 4), per_req + 1)
    # the pool refuses a T0 smaller than one slot's ceiling, so cap this
    # rung's max_len to what the longest turn actually needs
    rung_max_len = min(max_len, page_len * (per_req + 1))
    tails = [[rng.integers(1, vocab, tail_len, dtype=np.int32)
              for _ in range(n_turns)] for _ in range(n_sess)]

    def run(num_pages, tiers_kw):
        kv = {"enabled": True, "page_len": page_len}
        if num_pages:
            kv["num_pages"] = num_pages
        if tiers_kw:
            kv["tiers"] = {"enabled": True, **tiers_kw}
        srv = ServingEngine(engine, num_slots=slots, prefill_chunk=chunk,
                            max_len=rung_max_len, max_queue=args.max_queue,
                            max_new_tokens=budget, kvcache=kv)
        warm(srv, [{"prompt": tails[0][0][: page_len // 2], "max_new": 2}])
        outputs = []
        hist = [np.array([], np.int32) for _ in range(n_sess)]
        t0 = time.monotonic()
        for turn in range(n_turns):
            prompts = [np.concatenate([hist[s], tails[s][turn]]).astype(np.int32)
                       for s in range(n_sess)]
            rids = [srv.submit(prompts[s], max_new_tokens=budget,
                               temperature=0.0, session_id=f"tier-sess-{s}")
                    for s in range(n_sess)]
            res = srv.drain(max_steps=100_000)
            for s, rid in enumerate(rids):
                gen = np.asarray(res[rid].generated, np.int32)
                outputs.append(gen)
                hist[s] = np.concatenate([prompts[s], gen]).astype(np.int32)
        makespan = time.monotonic() - t0
        toks = sum(len(o) for o in outputs)
        st = srv.stats()
        rejected = int(st.get("rejected", 0))
        tiers = st.get("kvcache", {}).get("tiers")
        if getattr(srv, "_tiers", None) is not None:
            srv._tiers.close()  # stop the migration worker between runs
        return outputs, toks / max(makespan, 1e-9), rejected, tiers

    t2_dir = tempfile.mkdtemp(prefix="ds_kvtiers_")
    # the all-HBM pool holds the parked working set AND every active
    # slot's upfront claim comfortably below the default demote
    # watermark — no reclaim or demotion pressure, the true T0 baseline
    hbm_pages = int((ws_pages + slots * per_req) / 0.7) + 2
    try:
        out_ref, tps_ref, rej_ref, _ = run(hbm_pages, None)
        out_res, tps_res, rej_res, tiers_res = run(hbm_pages, {
            "host_pages": t0_usable, "disk_dir": os.path.join(t2_dir, "res"),
        })
        out_4x, tps_4x, rej_4x, tiers_4x = run(t0_usable + 1, {
            "host_pages": t0_usable,
            "disk_dir": os.path.join(t2_dir, "cold"),
            "residency_window": page_len,
            "demote_watermark": 0.5,
            "demote_batch": 8,
            "prefetch_ahead": slots,
        })
    finally:
        shutil.rmtree(t2_dir, ignore_errors=True)

    bit_identical = (
        len(out_4x) == len(out_ref) == len(out_res)
        and all(np.array_equal(a, b) for a, b in zip(out_4x, out_ref))
        and all(np.array_equal(a, b) for a, b in zip(out_res, out_ref))
    )
    ratio_res = round(tps_res / max(tps_ref, 1e-9), 3)
    swaps = (tiers_4x["demote_t0_t1"] + tiers_4x["promote_t1_t0"]
             + tiers_4x["promote_t2_t0"])
    rec = {
        "metric": f"serving_kvtiers_{model.replace('-', '_')}_4x",
        # the headline is the KV capacity multiple served at zero
        # rejects with bit-identical outputs — deterministic by
        # construction, so the perf sentinel can gate it with a tight
        # band (raw tok/s rides along below; too noisy on CPU runners)
        "value": round(ws_pages / t0_usable, 2),
        "unit": "x_hbm_kv_capacity",
        "bit_identical": bit_identical,
        "working_set_pages": ws_pages,
        "t0_pages": t0_usable,
        "oversubscription_x": round(ws_pages / t0_usable, 2),
        "tokens_per_s_4x": round(tps_4x, 1),
        "tokens_per_s_ref": round(tps_ref, 1),
        "tokens_per_s_resident": round(tps_res, 1),
        "tok_ratio_resident": ratio_res,
        "queue_full_4x": rej_4x,
        "swaps": swaps,
        "swap_hidden_ratio": tiers_4x["swap_hidden_ratio"],
        "demote_t0_t1": tiers_4x["demote_t0_t1"],
        "demote_t1_t2": tiers_4x["demote_t1_t2"],
        "promote_t1_t0": tiers_4x["promote_t1_t0"],
        "promote_t2_t1": tiers_4x["promote_t2_t1"],
        "promote_t2_t0": tiers_4x["promote_t2_t0"],
        "hits_t1": tiers_4x["hits_t1"],
        "hits_t2": tiers_4x["hits_t2"],
        "sessions": n_sess,
        "turns": n_turns,
        "num_slots": slots,
        "page_len": page_len,
        "max_len": rung_max_len,
    }
    emit(rec, rung="kvtiers")
    log(f"[kvtiers] {rec['oversubscription_x']}x working set: "
        f"{rec['tokens_per_s_4x']} tok/s (ref {rec['tokens_per_s_ref']}, resident "
        f"ratio {ratio_res}), {swaps} swaps, hidden "
        f"{rec['swap_hidden_ratio']:.0%}, bit_identical={bit_identical}, "
        f"queue_full={rej_4x}")
    if not bit_identical:
        raise SystemExit("[kvtiers] FAIL: tiered outputs diverge from all-HBM")
    if rej_4x or rej_res or rej_ref:
        raise SystemExit(f"[kvtiers] FAIL: ServingQueueFull raised "
                         f"(ref={rej_ref} resident={rej_res} 4x={rej_4x})")
    if swaps == 0:
        raise SystemExit("[kvtiers] FAIL: 4x run never exercised the tiers")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dryrun", action="store_true", help="tiny model on CPU")
    ap.add_argument("--model", default=None)
    ap.add_argument("--loads", default="0.5,1.0,2.0",
                    help="offered loads as fractions of measured capacity")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--max-new", type=int, default=None)
    ap.add_argument("--kv", default="both", choices=("both", "model", "int8"))
    ap.add_argument("--num-slots", type=int, default=None)
    ap.add_argument("--prefill-chunk", type=int, default=None)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--fleet", action="store_true",
                    help="fleet-failover mode (docs/serving.md §Fleet): a "
                         "3-replica FleetRouter under seeded Poisson load, "
                         "one replica killed mid-run and supervised back — "
                         "records availability + failover-p99-over-steady")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic-fleet mode (docs/serving.md §Elastic "
                         "fleet): an autoscaled fleet under ~10x one "
                         "replica's offered load with a forced mid-surge "
                         "scale-down + live KV migration — records "
                         "aggregate tokens/s, admitted-p99 TTFT, shed "
                         "rate, and scale reaction times")
    ap.add_argument("--kvcache", action="store_true",
                    help="paged-KV mode (docs/serving.md §Paged KV & prefix "
                         "caching): an 80%%-shared system-prompt batch plus "
                         "3-turn sessions, run with the cache on vs off — "
                         "records prefill-FLOPs reduction, hit rate, and "
                         "TTFT p50/p99 both ways at bit-identical outputs")
    ap.add_argument("--kvtiers", action="store_true",
                    help="KV-tiering mode (docs/serving.md §KV tiering): "
                         "a session fleet whose parked KV working set is "
                         "~4x the device page pool, vs an all-HBM "
                         "reference — records tokens/s at 4x, the "
                         "T0-resident overhead ratio, and the swap-hide "
                         "ratio at bit-identical outputs")
    ap.add_argument("--tenants", action="store_true",
                    help="mixed-tenant isolation mode (docs/serving.md "
                         "§Front-door): a quiet tenant's seeded stream "
                         "run solo vs next to a noisy tenant offered "
                         "10x its token-bucket quota — records the "
                         "quiet tenant's admitted p99 TTFT both ways "
                         "plus the noisy throttle rate")
    ap.add_argument("--overload", action="store_true",
                    help="overload-resilience mode: arm the estimated-TTFT "
                         "shedder (--slo-ttft-ms) and run 2x/4x offered load, "
                         "recording shed-rate + admitted-p99 TTFT")
    ap.add_argument("--slo-ttft-ms", type=float, default=500.0,
                    help="serving.slo_ttft_ms for --overload engines")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="export a Chrome-trace/Perfetto trace.json of the "
                         "run's spans (per-request lifecycles + step phases)")
    args = ap.parse_args()

    import jax

    import deepspeed_tpu
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.config.config import TelemetryConfig
    from deepspeed_tpu.serving import ServingEngine

    # arm the process plane before any engine is built; tracing only
    # when requested (the span buffer is a ring, but why pay for it)
    telemetry.configure(
        TelemetryConfig(trace=bool(args.trace), trace_path=args.trace or ""),
        label="bench_serving",
    )

    on_tpu = jax.default_backend() in ("tpu", "axon")
    if args.dryrun or not on_tpu:
        model, slots, chunk, max_len = "tiny", 4, 16, 128
        n_req, max_new, lo, hi = 12, 8, 4, 48
        quantize_bits = 0
    else:
        model, slots, chunk, max_len = (args.model or "gpt2-xl"), 8, 128, 512
        n_req, max_new, lo, hi = 32, 64, 32, 384
        quantize_bits = 8  # int8 weights: the serving-optimized decode path
    n_req = args.requests or n_req
    max_new = args.max_new or max_new
    slots = args.num_slots or slots
    chunk = args.prefill_chunk or chunk
    if args.overload and args.loads == "0.5,1.0,2.0":
        args.loads = "2.0,4.0"  # the shed regime, unless --loads overrides
    loads = [float(x) for x in args.loads.split(",") if x]

    t0 = time.monotonic()
    engine = deepspeed_tpu.init_inference(
        model=model, quantize_bits=quantize_bits, max_out_tokens=max_len,
        init_on_device=on_tpu and not args.dryrun,
    )
    log(f"engine ready in {time.monotonic()-t0:.1f}s (model={model})")
    workload = build_workload(
        n_req, lo, hi, max_new, args.seed, engine.model_config.vocab_size
    )

    if args.fleet:
        run_fleet_bench(engine, args, slots, chunk, max_len, max_new,
                        workload, model)
        if args.trace:
            path = telemetry.export_trace(args.trace)
            log(f"trace exported -> {path}")
        return

    if args.elastic:
        run_elastic_bench(engine, args, slots, chunk, max_len, max_new,
                          workload, model)
        if args.trace:
            path = telemetry.export_trace(args.trace)
            log(f"trace exported -> {path}")
        return

    if args.tenants:
        run_tenant_bench(engine, args, slots, chunk, max_len, max_new, model)
        if args.trace:
            path = telemetry.export_trace(args.trace)
            log(f"trace exported -> {path}")
        return

    if args.kvcache:
        run_kvcache_bench(engine, args, slots, chunk, max_len, max_new, model)
        if args.trace:
            path = telemetry.export_trace(args.trace)
            log(f"trace exported -> {path}")
        return

    if args.kvtiers:
        run_kvtiers_bench(engine, args, slots, chunk, max_len, max_new, model)
        if args.trace:
            path = telemetry.export_trace(args.trace)
            log(f"trace exported -> {path}")
        return

    kvs = ("model", "int8") if args.kv == "both" else (args.kv,)
    for kv in kvs:
        # dryrun engines are f32 but keep the "bf16" tag so the rung's
        # metric names stay stable across dev and TPU runs
        tag = "int8" if kv == "int8" else "bf16"

        def make_serving():
            kw = {}
            if args.overload:
                # arm the admission controller; the capacity measurement
                # below stays unshedded (closed-loop never queues deep)
                kw["slo_ttft_ms"] = args.slo_ttft_ms
            return ServingEngine(
                engine, num_slots=slots, prefill_chunk=chunk, max_len=max_len,
                kv_cache_dtype=kv, max_queue=args.max_queue, max_new_tokens=max_new,
                **kw,
            )

        tok_s, req_s, dt = run_closed_loop(make_serving, workload)
        log(f"[{tag}] closed-loop capacity: {tok_s:,.0f} tok/s, "
            f"{req_s:.2f} req/s over {dt:.1f}s")
        for load in loads:
            rec = run_load(make_serving, workload, max(req_s * load, 1e-3),
                           seed=args.seed + int(load * 1000))
            prefix = "serving_overload" if args.overload else "serving"
            rec = {
                "metric": f"{prefix}_{model.replace('-', '_')}_{tag}kv_load{load:g}",
                "value": rec.pop("tokens_per_s"),
                "unit": "tokens/s",
                "kv_cache_dtype": tag,
                "load_fraction": load,
                **({"slo_ttft_ms": args.slo_ttft_ms} if args.overload else {}),
                "num_slots": slots,
                "prefill_chunk": chunk,
                "max_len": max_len,
                "requests": n_req,
                **rec,
            }
            emit(rec)
            log(f"[{tag}] load {load:g}x: {rec['value']} tok/s, "
                f"ttft p50/p99 {rec['ttft_p50_ms']}/{rec['ttft_p99_ms']} ms, "
                f"tpot p50/p99 {rec['tpot_p50_ms']}/{rec['tpot_p99_ms']} ms, "
                f"queue {rec['queue_depth']}"
                + (f", shed_rate {rec['shed_rate']:.1%} "
                   f"(admitted p99 {rec['ttft_submit_p99_ms']} ms vs "
                   f"SLO {args.slo_ttft_ms:g})" if args.overload else ""))

    if args.trace:
        path = telemetry.export_trace(args.trace)
        log(f"trace exported -> {path}")


if __name__ == "__main__":
    main()
