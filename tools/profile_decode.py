"""Profile the XL decode loop on the chip: per-HLO-category device time
for the steady-state token scan (the instrument behind the decode
dispatch work — run after any decode-path change).

Run: python tools/profile_decode.py [model] [B] [new_tokens]
"""
import collections
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import deepspeed_tpu

    model = sys.argv[1] if len(sys.argv) > 1 else "gpt2-xl"
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    N = int(sys.argv[3]) if len(sys.argv) > 3 else 64

    engine = deepspeed_tpu.init_inference(model=model, max_out_tokens=512)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, engine.model_config.vocab_size, (B, 128), dtype=np.int32)
    out = engine.generate(prompt, max_new_tokens=N, do_sample=False)
    _ = int(np.asarray(out)[0, -1])  # warm + compile

    trace_dir = tempfile.mkdtemp(prefix="decode_trace_")
    with jax.profiler.trace(trace_dir):
        out = engine.generate(prompt, max_new_tokens=N, do_sample=False)
        _ = int(np.asarray(out)[0, -1])

    f = sorted(glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")))[-1]
    with gzip.open(f) as fh:
        data = json.load(fh)
    ev = [
        e for e in data["traceEvents"]
        if e.get("ph") == "X" and e.get("args") and e["args"].get("hlo_category")
    ]
    cat_t = collections.Counter()
    op_t = collections.Counter()
    total = 0
    for e in ev:
        c = e["args"]["hlo_category"]
        if c in ("while", "conditional", "call"):
            continue
        cat_t[c] += e["dur"]
        op_t[e.get("name", "?")[:70]] += e["dur"]
        total += e["dur"]
    print(f"total device time: {total/1e3:.1f} ms for {N} tokens -> {total/1e3/N:.2f} ms/token")
    print(f"\n{'hlo category':30s} {'ms/token':>9s}")
    for c, t in cat_t.most_common(12):
        print(f"{c:30s} {t/1e3/N:9.3f}")
    print(f"\n{'top ops':70s} {'ms/token':>9s}")
    for o, t in op_t.most_common(15):
        print(f"{o:70s} {t/1e3/N:9.3f}")


if __name__ == "__main__":
    main()
