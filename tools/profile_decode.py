"""Profile the XL decode loop on the chip: per-HLO-category device time
for the steady-state token scan (the instrument behind the decode
dispatch work — run after any decode-path change).  The cost walk is
the shared one in ``deepspeed_tpu.telemetry.attribution``; durations
are reported per TOKEN, not per step.

Run: python tools/profile_decode.py [model] [B] [new_tokens]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import deepspeed_tpu
    from deepspeed_tpu.telemetry.attribution import (
        format_trace_tables,
        profile_and_report,
    )

    model = sys.argv[1] if len(sys.argv) > 1 else "gpt2-xl"
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    N = int(sys.argv[3]) if len(sys.argv) > 3 else 64

    engine = deepspeed_tpu.init_inference(model=model, max_out_tokens=512)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, engine.model_config.vocab_size, (B, 128), dtype=np.int32)
    out = engine.generate(prompt, max_new_tokens=N, do_sample=False)
    _ = int(np.asarray(out)[0, -1])  # warm + compile

    def one_run():
        out = engine.generate(prompt, max_new_tokens=N, do_sample=False)
        _ = int(np.asarray(out)[0, -1])  # true sync inside the trace

    tables = profile_and_report(one_run, steps=1, denom=N)
    print(format_trace_tables(tables, unit="token"))


if __name__ == "__main__":
    main()
