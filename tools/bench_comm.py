"""Comm-strategy sweep: dense vs int8 vs 1-bit gradient exchange.

Drives the `comm-strategies` bench rung (bench.py) and runs standalone:

    python tools/bench_comm.py --dryrun          # 8 virtual CPU devices
    python tools/bench_comm.py --steps 16        # real devices

Two model families (the ISSUE-6 acceptance pair): a GPT-2 config (124M
on TPU, tiny-8L on the CPU dryrun) swept across comm.strategy
dense/int8/onebit, and a BERT s512 config (BERT-Large on TPU, tiny on
CPU) swept dense/int8 plus the **1-bit LAMB** frozen-exchange phase
(optimizer-level momentum compression — the large-batch rung of
arXiv:2104.06069).

Each record carries, per strategy:

* ``steps_per_s`` and the final-loss trajectory (parity vs dense);
* ``grad_exchange_bytes_hlo`` — collective bytes parsed from the
  compiled train executable (utils/hlo.py).  NB dense's per-micro
  reduction sits inside the accumulation scan, so its static text
  *undercounts* runtime bytes by ``gas``x; ``grad_exchange_bytes_step``
  applies that correction (and is what the >= 4x acceptance ratio is
  computed from);
* ``comm_bytes_model`` — the analytic model (comm/strategy.py);
* ``compiles`` — must be 1 per strategy (zero recompiles across steps).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# --dryrun must win before jax initializes (same recipe as tests/conftest.py)
if "--dryrun" in sys.argv:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def log(msg):
    print(f"[bench_comm] {msg}", file=sys.stderr, flush=True)


def emit(rec):
    print(json.dumps(rec), flush=True)
    from deepspeed_tpu.telemetry.regression import tool_history_emit

    # standalone runs feed the persistent bench history too (no-op when
    # the bench.py driver parent is the history writer)
    tool_history_emit(rec, rung="comm-strategies",
                      base_dir=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _tb_collective_bytes(engine):
    """Collective bytes of the ACTIVE train executable — the frozen one
    when a 1-bit optimizer has entered its compressed phase."""
    from deepspeed_tpu.utils.hlo import collective_bytes

    keys = [k for k in engine._compiled if isinstance(k, tuple) and k[0] == "train_batch"]
    frozen = [k for k in keys if k[1]]
    key = frozen[0] if frozen else keys[0]
    return collective_bytes(engine._compiled[key].as_text())


def _run_engine(model_fn, params, config, batches, steps, label, warm_steps=2):
    import deepspeed_tpu

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=params, config=config
    )
    # warm past any phase boundary (1-bit freeze_step recompiles once)
    losses = [float(engine.train_batch(b)) for b in batches(warm_steps)]
    t0 = time.time()
    losses += [float(engine.train_batch(b)) for b in batches(steps)]
    dt = (time.time() - t0) / steps
    log(f"[{label}] step={dt*1e3:.1f}ms loss={losses[-1]:.4f} compiles={engine.compilation_count}")
    return engine, losses, dt


def sweep_family(family: str, steps: int, on_tpu: bool):
    import jax

    import deepspeed_tpu  # noqa: F401

    n_dev = jax.device_count()
    rng = np.random.default_rng(0)

    if family == "gpt2":
        import dataclasses

        from deepspeed_tpu.models import gpt2

        cfg = (
            dataclasses.replace(gpt2.GPT2_SMALL, remat=False, scan_unroll=gpt2.GPT2_SMALL.n_layer)
            if on_tpu
            else dataclasses.replace(gpt2.GPT2_TINY, n_layer=4, n_embd=64, n_head=4, vocab_size=256)
        )
        micro_bs, seq = (4, 1024) if on_tpu else (1, 32)
        model_fn, init_fn, _ = gpt2.make_model(cfg)
        init = init_fn()

        def make_batches(global_bs):
            def batches(n):
                r = np.random.default_rng(1)  # same data per strategy
                for _ in range(n):
                    yield {"input_ids": r.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)}

            return batches

        opt_sweep = []
    else:  # bert-s512
        import dataclasses

        from deepspeed_tpu.models import bert

        base = bert.BERT_LARGE if on_tpu else bert.BERT_TINY
        seq = min(512, base.max_position_embeddings)
        cfg = dataclasses.replace(base, remat=False, scan_unroll=base.num_hidden_layers)
        micro_bs = 16 if on_tpu else 2
        model_fn, init_fn, _ = bert.make_model(cfg)
        init = init_fn()

        def make_batches(global_bs):
            def batches(n):
                r = np.random.default_rng(1)
                for _ in range(n):
                    ids = r.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)
                    yield {
                        "input_ids": ids,
                        "masked_lm_labels": np.where(
                            r.random((global_bs, seq)) < 0.15, ids, -100
                        ).astype(np.int32),
                        "next_sentence_label": r.integers(0, 2, (global_bs,), dtype=np.int32),
                    }

            return batches

        # the 1-bit LAMB rung: optimizer-level momentum compression
        # (frozen phase) rather than a comm.strategy grad exchange.
        # freeze_step=3: the variance estimate needs a few warmup steps
        # or the frozen denom is garbage (freeze_step=1 diverges)
        opt_sweep = [("onebit-lamb", {"type": "OneBitLamb", "params": {"lr": 1e-3, "freeze_step": 3}})]

    # gas=4: large-batch accumulation is where one-exchange-per-step
    # wins — dense reduces per micro batch, the compressed strategies
    # exchange once at the boundary
    gas = 4
    dense_bytes_step = None
    dense_losses = None
    runs = [("dense", None), ("int8", None), ("onebit", None)] + [
        (name, opt) for name, opt in opt_sweep
    ]
    for strat, opt_cfg in runs:
        config = {
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "bf16": {"enabled": True},
            "optimizer": opt_cfg or {"type": "Adam", "params": {"lr": 1e-4 if family == "gpt2" else 1e-3}},
            "steps_per_print": 100000,
        }
        if opt_cfg is None:
            config["comm"] = {"strategy": strat, "threshold_bytes": 0}
        label = f"{family}-{strat}"
        try:
            import jax as _jax

            init_copy = _jax.tree.map(np.copy, init)
            warm = 2 if opt_cfg is None else int(opt_cfg["params"].get("freeze_step", 0)) + 2
            engine, losses, dt = _run_engine(
                model_fn, init_copy, config,
                make_batches(micro_bs * gas * n_dev), steps, label, warm_steps=warm,
            )
        except Exception as e:  # noqa: BLE001 — one failed rung must not kill the sweep
            log(f"[{label}] FAILED: {str(e)[:300]}")
            emit({"metric": f"comm_strategy_{family}_{strat}", "skipped": True, "reason": str(e)[:300]})
            continue
        hlo_bytes = _tb_collective_bytes(engine)
        summ = engine.comm_summary()
        # dense's grad reduction runs per micro batch inside the scan —
        # static HLO text shows it once; correct to runtime bytes.  The
        # explicit strategies and the 1-bit frozen phase exchange ONCE
        # per step (their rows accumulate locally), no correction.
        once_per_step = engine._comm_explicit or engine._onebit_frozen
        bytes_step = hlo_bytes * (1 if once_per_step else gas)
        rec = {
            "metric": f"comm_strategy_{family}_{strat}",
            "value": round(1.0 / dt, 3),
            "unit": "steps/s",
            "comm_strategy": summ["strategy"] if opt_cfg is None else strat,
            "grad_exchange_bytes_hlo": int(hlo_bytes),
            "grad_exchange_bytes_step": int(bytes_step),
            "comm_bytes_model": summ["grad_exchange_bytes"],
            "final_loss": round(losses[-1], 5),
            "losses": [round(l, 5) for l in losses],
            "compiles": engine.compilation_count,
            "gas": gas,
            "micro_bs": micro_bs,
            "seq": seq,
        }
        if strat == "dense":
            dense_bytes_step = bytes_step
            dense_losses = losses
        else:
            if dense_bytes_step:
                rec["bytes_reduction_vs_dense"] = round(dense_bytes_step / max(bytes_step, 1), 2)
            if dense_losses:
                pairs = [(a, b) for a, b in zip(losses, dense_losses)]
                rec["loss_rel_dev_vs_dense"] = round(
                    float(np.mean([abs(a - b) / (abs(b) + 1e-9) for a, b in pairs])), 4
                )
        emit(rec)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dryrun", action="store_true", help="8 virtual CPU devices (handled pre-import)")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--families", default="gpt2,bert")
    args = ap.parse_args()

    import jax

    on_tpu = jax.default_backend() in ("tpu", "axon")
    steps = args.steps if args.steps is not None else (12 if on_tpu else 6)
    log(f"backend={jax.default_backend()} devices={jax.device_count()} steps={steps}")
    for family in args.families.split(","):
        sweep_family(family.strip(), steps, on_tpu)


if __name__ == "__main__":
    main()
