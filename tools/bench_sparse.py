"""Splash block-sparse attention vs dense flash: speed curve over
sequence length (the reference claims up to 6.3x at long sequences,
docs/_posts/2020-09-09-sparse-attention.md:32).

Run on the TPU chip: python tools/bench_sparse.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.attention.flash_attention import flash_attention
from deepspeed_tpu.ops.attention.sparse import BigBirdSparsityConfig, block_sparse_attention


def timed_chain(fn, q, k, v, iters=48):
    """Dependency-chained timing (block_until_ready is unreliable on
    tunneled backends): q is perturbed by a reduction of the output.
    ``iters`` amortizes the tunnel's ~100ms fixed dispatch RTT — at 8
    iters the floor is ~12ms/call and masks sub-10ms kernels."""

    @jax.jit
    def chain(q, k, v):
        def body(i, carry):
            q, s = carry
            o = fn(q, k, v)
            s2 = jnp.mean(o.astype(jnp.float32))
            return q + (s2 * 1e-12).astype(q.dtype), s + s2

        q, s = jax.lax.fori_loop(0, iters, body, (q, jnp.zeros((), jnp.float32)))
        return s

    out = chain(q, k, v)
    _ = float(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        _ = float(chain(q, k, v))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def grad_of(fn):
    """Full training backward: differentiate ALL of q/k/v and fold every
    grad into the result, or XLA dead-code-eliminates the dk/dv kernel
    of whichever backend splits its backward into separate programs and
    the comparison is asymmetric."""

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    def g(q, k, v):
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return dq + (jnp.sum(dk) + jnp.sum(dv)).astype(dq.dtype)

    return g


def main():
    H, hd, block = 12, 64, 128
    B = 1
    mode = sys.argv[1] if len(sys.argv) > 1 else "both"
    r = np.random.default_rng(0)
    print(f"{'seq':>6s} {'pass':>8s} {'dense flash':>12s} {'splash':>12s} {'speedup':>8s} {'density':>8s}")
    for T in (4096, 8192, 16384):
        sc = BigBirdSparsityConfig(
            num_heads=H, block=block, num_random_blocks=1,
            num_sliding_window_blocks=3, num_global_blocks=1, attention="unidirectional",
        )
        layout = sc.make_layout(T)
        density = float(layout.sum()) / layout[0].size / H
        q = jnp.asarray(r.standard_normal((B, H, T, hd)) * 0.3, jnp.bfloat16)
        k = jnp.asarray(r.standard_normal((B, H, T, hd)) * 0.3, jnp.bfloat16)
        v = jnp.asarray(r.standard_normal((B, H, T, hd)) * 0.3, jnp.bfloat16)

        dense = lambda q, k, v: flash_attention(q, k, v, causal=True)
        splash = lambda q, k, v: block_sparse_attention(
            q, k, v, layout, block, causal=True, backend="splash"
        )
        passes = []
        if mode in ("fwd", "both"):
            passes.append(("fwd", dense, splash))
        if mode in ("bwd", "both"):
            # training path: fwd + dedicated Pallas backward
            passes.append(("fwd+bwd", grad_of(dense), grad_of(splash)))
        for name, fd, fs in passes:
            t_dense = timed_chain(fd, q, k, v)
            t_splash = timed_chain(fs, q, k, v)
            print(
                f"{T:6d} {name:>8s} {t_dense*1e3:10.2f}ms {t_splash*1e3:10.2f}ms "
                f"{t_dense/t_splash:7.2f}x {density*100:7.1f}%",
                flush=True,
            )


if __name__ == "__main__":
    main()
